"""Admission control: per-tenant weighted-fair queues, bounded backlog,
priority-aware load shedding.

The shapes are the classic inference-serving ones (the ROADMAP's
"thousands of concurrent feeds" regime): every tenant owns a FIFO of
pending span micro-batches, service order across tenants is start-time
fair queuing (SFQ — each batch gets a virtual finish tag
``start + cost/weight``; the drain always serves the globally smallest
tag), and two backlog bounds provide backpressure:

- a per-tenant bound, so one runaway feed cannot monopolize the queue
  memory (its own overflow is shed, nobody else's), and
- a global bound (``ANOMOD_SERVE_MAX_BACKLOG``): when offered load
  exceeds capacity the controller sheds in PRIORITY order — an arriving
  batch may evict queued work of strictly lower priority (latest-served
  first, so the evicted work is what fair queuing would have reached
  last), and is itself shed when nothing lower-priority is queued.

Everything is host-side bookkeeping over integers and floats — no wall
clocks, no randomness — so a seeded overload replay is bit-reproducible
(the determinism contract tests/test_serve.py pins).

Registry costs scale with the ACTIVE tenant set, not the registered one
(the tiering PR's O(hot-set) contract): the per-tenant counters, backlog
depths and SFQ last-finish tags are created lazily on a tenant's first
offer, the registered fleet lives in one columnar spec table
(:class:`_SpecTable` — id/priority/weight arrays, ~26 exact bytes per
registered tenant instead of a spec-dict entry), and the admission
totals are maintained as a RUNNING sum at every mutation site, so
``totals()`` — called per tick by the flight recorder — is O(1) instead
of an O(registered) walk.  Same integers on every path (pinned).

Two drain/shed engines implement the same contract
(``ANOMOD_SERVE_NATIVE_DRAIN``): the original per-span Python heap pair
(``off`` — kept as the parity oracle) and the columnar engine
(:class:`_ColumnarSFQ`, the default) whose candidate scans run over
parallel NumPy arrays — in the native runtime (``anomod_sfq_drain`` /
``anomod_sfq_victim``) when it loads, pure ``lexsort`` otherwise.  All
three paths are pinned byte-identical: same served order, same shed and
eviction victims, same SFQ virtual-time floats.
"""

from __future__ import annotations

import ctypes
import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod import obs
from anomod.schemas import SpanBatch

#: default scheduler weight per priority class (0 = most important).
PRIORITY_WEIGHTS = {0: 4.0, 1: 2.0, 2: 1.0}


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's static admission contract."""
    tenant_id: int
    name: str
    priority: int = 1          # 0 = gold, 1 = silver, 2 = bronze
    weight: float = 0.0        # 0 -> PRIORITY_WEIGHTS[priority]
    rate_spans_per_s: float = 0.0   # offered-load hint (traffic generator)

    def effective_weight(self) -> float:
        if self.weight > 0:
            return self.weight
        return PRIORITY_WEIGHTS.get(self.priority, 1.0)


@dataclasses.dataclass
class QueuedBatch:
    """One admitted micro-batch waiting for the batcher."""
    tenant_id: int
    seq: int                   # global admission sequence number
    spans: SpanBatch
    n_spans: int
    priority: int
    enqueued_s: float          # virtual admission time
    finish_tag: float          # SFQ virtual finish time


@dataclasses.dataclass
class TenantCounters:
    offered_spans: int = 0
    admitted_spans: int = 0
    served_spans: int = 0
    shed_spans: int = 0
    offered_batches: int = 0
    served_batches: int = 0
    shed_batches: int = 0
    # evictions are the subset of shed batches destroyed AFTER admission
    # (displaced by a higher-priority arrival) — counted separately so
    # the flight recorder's admission plane journals them per tick
    evicted_batches: int = 0


class _LazyCounters(dict):
    """Per-tenant counters created on first touch — the registered
    fleet never materializes a row (the O(hot-set) registry contract);
    external readers of a never-offered tenant see zeros, same as the
    eager dict before."""

    def __missing__(self, tid: int) -> TenantCounters:
        c = self[tid] = TenantCounters()
        return c


class _SpecTable:
    """The registered fleet as columns: tenant id, priority, resolved
    SFQ weight and the rate hint as parallel arrays, names as a tuple
    of references — ~26 exact bytes per registered tenant where the
    spec dict paid a dict entry + bookkeeping rows each.  Dense ids
    (0..n-1, every generated fleet) index straight into the arrays;
    anything else goes through a side index.  ``__getitem__``
    rematerializes a :class:`TenantSpec` for report/test callers —
    never on the offer/drain hot path, which reads
    :meth:`priority_of` / :meth:`weight_of`."""

    __slots__ = ("ids", "pri", "wt", "rate", "names", "_index")

    def __init__(self, tenants: Sequence[TenantSpec]):
        self.ids = np.asarray([t.tenant_id for t in tenants], np.int64)
        if len(np.unique(self.ids)) != len(self.ids):
            raise ValueError("duplicate tenant_id in tenant specs")
        self.pri = np.asarray([t.priority for t in tenants], np.int16)
        self.wt = np.asarray([t.effective_weight() for t in tenants],
                             np.float64)
        self.rate = np.asarray([t.rate_spans_per_s for t in tenants],
                               np.float64)
        self.names = tuple(t.name for t in tenants)
        n = len(self.ids)
        dense = n > 0 and self.ids[0] == 0 and self.ids[n - 1] == n - 1 \
            and bool((self.ids == np.arange(n, dtype=np.int64)).all())
        self._index: Optional[Dict[int, int]] = None if dense \
            else {int(t): i for i, t in enumerate(self.ids)}

    def _row(self, tid: int) -> int:
        if self._index is None:
            if 0 <= tid < len(self.ids):
                return tid
            raise KeyError(tid)
        return self._index[tid]

    def priority_of(self, tid: int) -> int:
        return int(self.pri[self._row(tid)])

    def weight_of(self, tid: int) -> float:
        return float(self.wt[self._row(tid)])

    def __len__(self) -> int:
        return len(self.ids)

    def __contains__(self, tid: int) -> bool:
        try:
            self._row(tid)
        except KeyError:
            return False
        return True

    def __iter__(self):
        return iter(int(t) for t in self.ids)

    def __getitem__(self, tid: int) -> TenantSpec:
        i = self._row(tid)
        return TenantSpec(tenant_id=int(self.ids[i]),
                          name=self.names[i],
                          priority=int(self.pri[i]),
                          weight=0.0 if self.wt[i]
                          == PRIORITY_WEIGHTS.get(int(self.pri[i]), 1.0)
                          else float(self.wt[i]),
                          rate_spans_per_s=float(self.rate[i]))

    def nbytes(self) -> int:
        """Exact column bytes + 8 nominal per name reference (the
        strings are owned by the caller's spec objects) + the sparse
        index entries where ids are not dense — the census admission
        plane's per-REGISTERED price."""
        b = int(self.ids.nbytes + self.pri.nbytes + self.wt.nbytes
                + self.rate.nbytes) + 8 * len(self.names)
        if self._index is not None:
            b += 64 * len(self._index)
        return b


class _ColumnarSFQ:
    """Struct-of-arrays mirror of the SFQ drain/evict heap pair.

    The heap engine pays per-batch Python on the serve hot path: one
    heappush onto BOTH heaps per admitted batch, lazy-deletion pops,
    and an amortized evict-heap compaction.  Here the pending-batch
    book is five parallel columns (finish tag, admission seq, span
    count, priority, alive mask) and the two hot scans become kernels
    over them:

    - drain selection: sort the alive slots by ``(finish_tag, seq)`` —
      exactly the drain heap's pop order (seqs are unique) — then walk
      the budget down with the SAME sequential float64 subtraction the
      heap loop performs (serve while ``remaining > 0``, one-batch
      overdraw included), and
    - shed victim: lexicographic argmax of ``(priority, finish_tag,
      seq)`` over the alive slots — exactly what the lazy evict heap's
      top names.

    Both kernels run in the native runtime (GIL released) when it
    loads, with a pure-NumPy fallback (``lexsort`` + the same walk)
    otherwise; the per-batch bookkeeping (counters, virtual-time floor,
    :class:`QueuedBatch` emission) stays in the controller unchanged,
    so all three engines are byte-identical (tests/test_serve.py pins
    heap == columnar-numpy == columnar-native).
    """

    __slots__ = ("fin", "seq", "nsp", "pri", "alive", "engine", "_lib",
                 "_slot_of", "_free", "_n", "_out",
                 "_p_fin", "_p_seq", "_p_nsp", "_p_pri", "_p_alive",
                 "_p_out")

    def __init__(self, cap: int = 256, require_native: bool = False):
        from anomod.io import native as io_native
        self._lib = io_native.sfq_kernels(require=require_native)
        self.engine = "native" if self._lib is not None else "numpy"
        cap = max(int(cap), 16)
        self.fin = np.zeros(cap, np.float64)
        self.seq = np.zeros(cap, np.int64)
        self.nsp = np.zeros(cap, np.int64)
        self.pri = np.zeros(cap, np.int64)
        self.alive = np.zeros(cap, np.uint8)
        self._slot_of: Dict[int, int] = {}
        self._free: List[int] = []
        self._n = 0                       # slot high-water mark
        self._out = np.empty(cap, np.int64)
        self._rebind()

    def _rebind(self) -> None:
        # marshal the column pointers ONCE per (re)allocation — the
        # StagePlan discipline: per-call ctypes extraction costs as much
        # as the scan it wraps on a small backlog
        self._p_fin = self.fin.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double))
        self._p_seq = self.seq.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64))
        self._p_nsp = self.nsp.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64))
        self._p_pri = self.pri.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64))
        self._p_alive = self.alive.ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8))
        self._p_out = self._out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64))

    def _grow(self) -> None:
        cap = len(self.fin) * 2
        for name in ("fin", "seq", "nsp", "pri", "alive"):
            old = getattr(self, name)
            new = np.zeros(cap, old.dtype)
            new[:len(old)] = old
            setattr(self, name, new)
        self._out = np.empty(cap, np.int64)
        self._rebind()

    def add(self, qb: "QueuedBatch") -> None:
        if self._free:
            slot = self._free.pop()
        else:
            if self._n >= len(self.fin):
                self._grow()
            slot = self._n
            self._n += 1
        self.fin[slot] = qb.finish_tag
        self.seq[slot] = qb.seq
        self.nsp[slot] = qb.n_spans
        self.pri[slot] = qb.priority
        self.alive[slot] = 1
        self._slot_of[qb.seq] = slot

    def remove(self, seq: int) -> None:
        slot = self._slot_of.pop(seq)
        self.alive[slot] = 0
        self._free.append(slot)

    def select(self, budget: float) -> List[int]:
        """Admission seqs served by ``budget`` spans, in drain order."""
        n = self._n
        if not self._slot_of:
            return []
        if self._lib is not None:
            count = self._lib.anomod_sfq_drain(
                self._p_fin, self._p_seq, self._p_nsp, self._p_alive,
                n, ctypes.c_double(budget), self._p_out)
            if count >= 0:
                return [int(self.seq[s]) for s in self._out[:count]]
        idx = np.flatnonzero(self.alive[:n])
        order = np.lexsort((self.seq[idx], self.fin[idx]))
        out: List[int] = []
        remaining = float(budget)
        for slot in idx[order]:
            if not remaining > 0:
                break
            remaining -= int(self.nsp[slot])
            out.append(int(self.seq[slot]))
        return out

    def victim(self) -> Optional[int]:
        """Admission seq of the batch the evict heap's top would name
        (lexicographic max of (priority, finish_tag, seq) over the
        alive slots); None when nothing is queued."""
        n = self._n
        if not self._slot_of:
            return None
        if self._lib is not None:
            got = self._lib.anomod_sfq_victim(
                self._p_fin, self._p_seq, self._p_pri, self._p_alive, n)
            if got >= 0:
                return int(self.seq[got])
        idx = np.flatnonzero(self.alive[:n])
        k = np.lexsort((self.seq[idx], self.fin[idx], self.pri[idx]))[-1]
        return int(self.seq[idx[k]])


class AdmissionController:
    """Weighted-fair admission over a bounded multi-tenant backlog."""

    def __init__(self, tenants: Sequence[TenantSpec],
                 max_backlog: int = 200_000,
                 max_tenant_backlog: Optional[int] = None,
                 drain_engine: Optional[str] = None):
        if max_backlog < 1:
            raise ValueError("max_backlog must be >= 1 span")
        if drain_engine is None:
            from anomod.config import get_config
            drain_engine = get_config().serve_native_drain
        if drain_engine not in ("auto", "on", "off"):
            raise ValueError(
                f"drain_engine must be auto, on or off, got "
                f"{drain_engine!r}")
        #: the resolved drain/shed engine: "heap" (the Python oracle),
        #: "numpy" or "native" (both columnar) — what the flight header
        #: and `anomod validate` surface
        self.drain_engine = "heap"
        self._col: Optional[_ColumnarSFQ] = None
        if drain_engine != "off":
            self._col = _ColumnarSFQ(require_native=(drain_engine == "on"))
            self.drain_engine = self._col.engine
        # registered fleet: one columnar table, not a dict of specs —
        # O(registered) exact bytes, O(1)-ish lookups; raises the same
        # duplicate-id ValueError the dict comprehension used to
        self.specs = _SpecTable(tenants)
        self.max_backlog = int(max_backlog)
        self.max_tenant_backlog = int(max_tenant_backlog
                                      if max_tenant_backlog is not None
                                      else max(max_backlog // 8, 1))
        # ACTIVE-tenant registries: rows materialize on first offer, so
        # a million-registered fleet with a thousand live feeds pays for
        # a thousand rows (the tiering PR's O(hot-set) contract)
        self.counters: Dict[int, TenantCounters] = _LazyCounters()
        # running totals, bumped at every counter mutation site below —
        # totals() is O(1), the flight recorder calls it every tick
        self._tot = TenantCounters()
        self.backlog_spans = 0
        self.peak_backlog_spans = 0
        self._tenant_backlog: Dict[int, int] = {}
        # per-priority backlog totals: the eviction feasibility check
        # must know how much strictly-lower-priority work is queued
        # BEFORE destroying any of it
        self._priority_backlog: Dict[int, int] = {}
        # SFQ state: system virtual time + per-tenant last finish tag
        # (lazy: a tenant that never offers never gets a tag)
        self._vtime = 0.0
        self._last_finish: Dict[int, float] = {}
        self._seq = 0
        self._alive: Dict[int, QueuedBatch] = {}      # seq -> batch
        # drain heap: smallest finish tag first (seq breaks ties
        # deterministically); evict heap: lowest priority (largest
        # number) first, then latest finish tag — the work fair queuing
        # would serve last.  Both use lazy deletion against _alive.
        self._drain_heap: List[Tuple[float, int]] = []
        self._evict_heap: List[Tuple[int, float, int]] = []
        self._evict_stale = 0
        # registry mirrors (anomod.obs): cached handles — offer/drain run
        # per micro-batch on the serving hot path
        self._obs_offered = obs.counter("anomod_serve_offered_spans_total")
        self._obs_admitted = obs.counter("anomod_serve_admitted_spans_total")
        self._obs_served = obs.counter("anomod_serve_served_spans_total")
        self._obs_shed = obs.counter("anomod_serve_shed_spans_total")
        self._obs_evicted = obs.counter("anomod_serve_evicted_batches_total")
        self._obs_backlog = obs.gauge("anomod_serve_backlog_spans")
        self._obs_tenant_backlog = obs.gauge(
            "anomod_serve_max_tenant_backlog_spans")

    def _obs_depths(self) -> None:
        self._obs_backlog.set(self.backlog_spans)
        self._obs_tenant_backlog.set(
            max(self._tenant_backlog.values(), default=0))

    # -- admission --------------------------------------------------------

    def offer(self, tenant_id: int, spans: SpanBatch,
              now_s: float) -> bool:
        """Admit (enqueue) or shed one tenant micro-batch.

        Returns True iff admitted.  Shedding is deterministic:
        per-tenant overflow sheds the arrival; global overflow evicts
        strictly-lower-priority queued work first and sheds the arrival
        only when none exists.
        """
        priority = self.specs.priority_of(tenant_id)
        n = spans.n_spans
        c = self.counters[tenant_id]
        c.offered_spans += n
        c.offered_batches += 1
        self._tot.offered_spans += n
        self._tot.offered_batches += 1
        self._obs_offered.inc(n)
        if n == 0:
            return False
        # both bounds refuse a batch only when queued work already exists
        # (the admission mirror of drain()'s one-batch overdraw): a batch
        # wider than a bound must still admit against an empty queue, or
        # it would be starved forever at ANY load
        backlog = self._tenant_backlog.get(tenant_id, 0)
        if backlog and backlog + n > self.max_tenant_backlog:
            c.shed_spans += n
            c.shed_batches += 1
            self._tot.shed_spans += n
            self._tot.shed_batches += 1
            self._obs_shed.inc(n)
            return False
        if self.backlog_spans and self.backlog_spans + n > self.max_backlog:
            # transactional eviction: only destroy lower-priority work if
            # enough of it exists to actually admit the arrival —
            # otherwise evicting would lose BOTH the victims and the
            # arrival (shed the arrival alone instead).  Emptying the
            # whole queue also admits (the empty-queue overdraw above),
            # so the headroom requirement caps at the current backlog.
            needed = min(self.backlog_spans + n - self.max_backlog,
                         self.backlog_spans)
            evictable = sum(v for p, v in self._priority_backlog.items()
                            if p > priority)
            if evictable < needed:
                c.shed_spans += n
                c.shed_batches += 1
                self._tot.shed_spans += n
                self._tot.shed_batches += 1
                self._obs_shed.inc(n)
                return False
        while self.backlog_spans and self.backlog_spans + n > self.max_backlog:
            victim = self._pop_eviction_candidate(priority)
            if victim is None:           # unreachable given the check above
                c.shed_spans += n
                c.shed_batches += 1
                self._tot.shed_spans += n
                self._tot.shed_batches += 1
                self._obs_shed.inc(n)
                return False
            vc = self.counters[victim.tenant_id]
            vc.shed_spans += victim.n_spans
            vc.shed_batches += 1
            vc.evicted_batches += 1
            vc.admitted_spans -= victim.n_spans
            self._tot.shed_spans += victim.n_spans
            self._tot.shed_batches += 1
            self._tot.evicted_batches += 1
            self._tot.admitted_spans -= victim.n_spans
            self._obs_shed.inc(victim.n_spans)
            self._obs_evicted.inc()
            self._remove(victim)
        start = max(self._vtime, self._last_finish.get(tenant_id, 0.0))
        finish = start + n / self.specs.weight_of(tenant_id)
        self._last_finish[tenant_id] = finish
        qb = QueuedBatch(tenant_id=tenant_id, seq=self._seq, spans=spans,
                         n_spans=n, priority=priority,
                         enqueued_s=now_s, finish_tag=finish)
        self._seq += 1
        self._alive[qb.seq] = qb
        if self._col is not None:
            self._col.add(qb)
        else:
            heapq.heappush(self._drain_heap, (qb.finish_tag, qb.seq))
            heapq.heappush(self._evict_heap,
                           (-qb.priority, -qb.finish_tag, -qb.seq))
        self.backlog_spans += n
        self._tenant_backlog[tenant_id] = backlog + n
        self._priority_backlog[priority] = \
            self._priority_backlog.get(priority, 0) + n
        self.peak_backlog_spans = max(self.peak_backlog_spans,
                                      self.backlog_spans)
        c.admitted_spans += n
        self._tot.admitted_spans += n
        self._obs_admitted.inc(n)
        self._obs_depths()
        return True

    def _pop_eviction_candidate(self, incoming_priority: int):
        """The queued batch a higher-priority arrival may displace:
        strictly lower priority than the arrival, lowest class first,
        latest finish tag first.  None when nothing qualifies."""
        if self._col is not None:
            seq = self._col.victim()
            if seq is None:
                return None
            qb = self._alive[seq]
            # the columnar argmax is the GLOBAL max priority number —
            # if even it is not strictly lower than the arrival,
            # nothing queued is (the lazy heap's top-check, restated)
            return qb if qb.priority > incoming_priority else None
        while self._evict_heap:
            neg_pri, neg_fin, neg_seq = self._evict_heap[0]
            qb = self._alive.get(-neg_seq)
            if qb is None:                      # already drained/evicted
                heapq.heappop(self._evict_heap)
                continue
            if -neg_pri <= incoming_priority:
                return None                     # nothing strictly lower
            heapq.heappop(self._evict_heap)
            return qb
        return None

    def _remove(self, qb: QueuedBatch) -> None:
        del self._alive[qb.seq]
        self.backlog_spans -= qb.n_spans
        self._tenant_backlog[qb.tenant_id] -= qb.n_spans
        self._priority_backlog[qb.priority] -= qb.n_spans
        if self._col is not None:
            self._col.remove(qb.seq)
            return
        # the evict heap prunes lazily only when overflow consults its
        # top; a long never-overloaded run would otherwise accumulate one
        # stale entry per drained batch forever — compact when stale
        # entries dominate (amortized O(1) per removal)
        self._evict_stale += 1
        if self._evict_stale > max(64, len(self._alive)):
            self._evict_heap = [(-q.priority, -q.finish_tag, -q.seq)
                                for q in self._alive.values()]
            heapq.heapify(self._evict_heap)
            self._evict_stale = 0

    # -- drain ------------------------------------------------------------

    def drain(self, budget_spans: float) -> List[QueuedBatch]:
        """Serve up to ``budget_spans`` in weighted-fair order.

        The budget may overdraw by at most one batch (batches are never
        split — the batcher needs them whole for replay parity), so a
        batch wider than a whole tick's budget still drains instead of
        deadlocking the queue.
        """
        if self._col is not None:
            out = []
            for seq in self._col.select(float(budget_spans)):
                qb = self._alive[seq]
                self._remove(qb)
                self._vtime = max(
                    self._vtime, qb.finish_tag - qb.n_spans
                    / self.specs.weight_of(qb.tenant_id))
                c = self.counters[qb.tenant_id]
                c.served_spans += qb.n_spans
                c.served_batches += 1
                self._tot.served_spans += qb.n_spans
                self._tot.served_batches += 1
                self._obs_served.inc(qb.n_spans)
                out.append(qb)
            if out:
                self._obs_depths()
            return out
        out: List[QueuedBatch] = []
        remaining = float(budget_spans)
        while remaining > 0 and self._drain_heap:
            fin, seq = self._drain_heap[0]
            qb = self._alive.get(seq)
            if qb is None:                      # evicted under overload
                heapq.heappop(self._drain_heap)
                continue
            heapq.heappop(self._drain_heap)
            self._remove(qb)
            self._vtime = max(self._vtime, fin - qb.n_spans
                              / self.specs.weight_of(qb.tenant_id))
            remaining -= qb.n_spans
            c = self.counters[qb.tenant_id]
            c.served_spans += qb.n_spans
            c.served_batches += 1
            self._tot.served_spans += qb.n_spans
            self._tot.served_batches += 1
            self._obs_served.inc(qb.n_spans)
            out.append(qb)
        if out:
            self._obs_depths()
        return out

    # -- report helpers ---------------------------------------------------

    def totals(self) -> TenantCounters:
        # O(1): the running sum, not a walk over per-tenant rows — the
        # flight recorder calls this every tick against fleets where
        # registered ≫ active
        return dataclasses.replace(self._tot)

    def per_priority(self) -> Dict[int, TenantCounters]:
        out: Dict[int, TenantCounters] = {}
        for tid, c in self.counters.items():
            pri = self.specs.priority_of(tid)
            acc = out.setdefault(pri, TenantCounters())
            for f in dataclasses.fields(TenantCounters):
                setattr(acc, f.name,
                        getattr(acc, f.name) + getattr(c, f.name))
        return out

    def tenant_backlog(self, tenant_id: int) -> int:
        """Queued spans for one tenant (0 when it never offered) — the
        demotion plane's skip-if-queued check."""
        return self._tenant_backlog.get(tenant_id, 0)

    def spec_table_nbytes(self) -> int:
        """Exact resident bytes of the registered-fleet spec table —
        the census admission plane's per-REGISTERED price."""
        return self.specs.nbytes()
