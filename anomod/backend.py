"""Backend dispatch: the BASELINE.json ``backend={cpu, jax-tpu}`` switch.

``cpu`` = pure numpy (the correctness oracle); ``jax`` / ``jax-tpu`` = jax.numpy
on whatever platform JAX selected (CPU mesh in tests, the real chip under
axon).  Numeric modules take an ``xp`` array namespace so the same expression
tree runs on either; JAX-only paths (jit/pallas) live in anomod.ops and
anomod.models and are reached when backend != cpu.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from anomod.config import get_config

_JAX_BACKENDS = ("jax", "jax-tpu", "tpu")


def resolve(backend: str | None = None) -> str:
    b = backend or get_config().backend
    return "jax" if b in _JAX_BACKENDS else "cpu"


def xp(backend: str | None = None) -> Any:
    """Array namespace for the chosen backend."""
    if resolve(backend) == "jax":
        import jax.numpy as jnp
        return jnp
    return np


def to_host(arr: Any) -> np.ndarray:
    return np.asarray(arr)


def device_put(arr: np.ndarray, backend: str | None = None) -> Any:
    if resolve(backend) == "jax":
        import jax
        return jax.device_put(arr)
    return arr
