"""Anomaly detection + root-cause ranking (the minimum end-to-end slice).

Per-service p99-latency inflation z-scores against the normal baseline,
fused with span error rates and log error rates — the quantitative version of
the reference's manual sanity checks (SN_collection-scripts/README.md:104-106:
"CPU fault ⇒ system_cpu_usage > 90%", error plateaus, etc.).  The numpy path
is the correctness oracle (BASELINE.json config 1); the JAX path is the same
expression tree on device.

Evaluation uses the chaos ground-truth labels (anomod.labels): top-k hit-rate
of the culprit service over the 2x12 fault experiments, plus experiment-level
detection accuracy.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from anomod import backend as backend_mod
from anomod import labels as labels_mod
from anomod.graph import ServiceStats, service_stats
from anomod.schemas import Experiment, LOG_ERROR


class ServiceFeatures(NamedTuple):
    """Per-service feature matrix for one experiment — fixed [S, F] shape."""
    services: Tuple[str, ...]
    x: np.ndarray  # float32 [S, F]


FEATURES = ("lat_p99_log", "lat_p50_log", "err_rate", "log_err_rate",
            "span_count_log", "lat_mean_log", "metric_level_log",
            "api_err_rate", "api_lat_log", "coverage_ratio",
            # level-keyed metric features: mean log-level of the series whose
            # metric family belongs to each anomaly-level group — the
            # reference keys its catalog by fault level
            # (metric_collector.py:37-104), so the detector sees the same
            # grouping (performance / service / database)
            "metric_perf_log", "metric_service_log", "metric_db_log")

_LEVEL_FEATURES = ("performance", "service", "database")  # cols 10..12


def extract_features(exp: Experiment,
                     services: Tuple[str, ...]) -> ServiceFeatures:
    """[S, F] features over all five modalities: spans, logs, metrics, API
    responses (per-endpoint stats attributed to the owning service via the
    gateway route tables), and code coverage (per-service line ratio)."""
    S = len(services)
    svc_index = {s: i for i, s in enumerate(services)}
    st = service_stats(exp.spans, services) if exp.spans is not None else None
    x = np.zeros((S, len(FEATURES)), np.float32)
    if st is not None:
        x[:, 0] = np.log1p(st.lat_p99_us)
        x[:, 1] = np.log1p(st.lat_p50_us)
        x[:, 2] = st.err_rate
        x[:, 4] = np.log1p(st.count)
        x[:, 5] = np.log1p(st.lat_mean_us)
    if exp.logs is not None:
        remap = np.array([svc_index.get(s, -1) for s in exp.logs.services] or [-1],
                         np.int32)
        svc = remap[exp.logs.service]
        keep = svc >= 0
        tot = np.zeros(S, np.int64)
        err = np.zeros(S, np.int64)
        np.add.at(tot, svc[keep], 1)
        np.add.at(err, svc[keep], (exp.logs.level[keep] == LOG_ERROR).astype(np.int64))
        with np.errstate(invalid="ignore"):
            x[:, 3] = np.where(tot > 0, err / np.maximum(tot, 1), 0.0)
    if exp.metrics is not None and len(exp.metrics.services):
        from anomod.metrics_catalog import level_metric_names
        m = exp.metrics
        # mean log-level of all series attributed to each service
        series_to_svc = np.array(
            [svc_index.get(m.services[s] if s >= 0 else "", -1)
             for s in m.series_service], np.int32)
        sample_svc = series_to_svc[m.series]
        keep = (sample_svc >= 0) & np.isfinite(m.value)
        logv = np.log1p(np.abs(np.where(np.isfinite(m.value), m.value, 0.0)))
        tot = np.zeros(S, np.float64)
        cnt = np.zeros(S, np.int64)
        np.add.at(tot, sample_svc[keep], logv[keep])
        np.add.at(cnt, sample_svc[keep], 1)
        with np.errstate(invalid="ignore"):
            x[:, 6] = np.where(cnt > 0, tot / np.maximum(cnt, 1), 0.0)
        # level-keyed means over the catalog's anomaly-level groups
        for li, level in enumerate(_LEVEL_FEATURES):
            names = set(level_metric_names(exp.testbed, level))
            in_level = np.array([n in names for n in m.metric_names], np.bool_)
            keep_l = keep & in_level[m.metric]
            tot_l = np.zeros(S, np.float64)
            cnt_l = np.zeros(S, np.int64)
            np.add.at(tot_l, sample_svc[keep_l], logv[keep_l])
            np.add.at(cnt_l, sample_svc[keep_l], 1)
            with np.errstate(invalid="ignore"):
                x[:, 10 + li] = np.where(cnt_l > 0,
                                         tot_l / np.maximum(cnt_l, 1), 0.0)
    if exp.api is not None and exp.api.n_records:
        from anomod.suite import endpoint_owner
        owner = np.array([svc_index.get(endpoint_owner(e, exp.testbed), -1)
                          for e in exp.api.endpoints], np.int32)
        rec_svc = owner[exp.api.endpoint]
        keep = rec_svc >= 0
        tot = np.zeros(S, np.int64)
        err = np.zeros(S, np.int64)
        lat = np.zeros(S, np.float64)
        np.add.at(tot, rec_svc[keep], 1)
        np.add.at(err, rec_svc[keep], (exp.api.status[keep] >= 500).astype(np.int64))
        np.add.at(lat, rec_svc[keep], np.log1p(exp.api.latency_ms[keep]))
        with np.errstate(invalid="ignore"):
            x[:, 7] = np.where(tot > 0, err / np.maximum(tot, 1), 0.0)
            x[:, 8] = np.where(tot > 0, lat / np.maximum(tot, 1), 0.0)
    if exp.coverage is not None and len(exp.coverage.services):
        ratio = exp.coverage.service_ratio()
        for ci, svc in enumerate(exp.coverage.services):
            si = svc_index.get(svc, -1)
            if si >= 0:
                x[si, 9] = ratio[ci]
    return ServiceFeatures(services=services, x=x)


# Score weights: latency inflation, error-rate delta, log-error delta,
# per-service metric level rise, API error/latency deltas, coverage shift.
_W_LAT, _W_ERR, _W_LOG, _W_MET = 1.0, 4.0, 2.0, 0.5
_W_API_ERR, _W_API_LAT, _W_COV = 2.0, 0.5, 1.0


def service_scores(feat: np.ndarray, base: np.ndarray,
                   backend: Optional[str] = None):
    """Anomaly score per service vs the normal-baseline feature matrix.

    score = w_lat * log-p99 inflation + w_err * Δerr_rate + w_log * Δlog_err.
    Pure function of two [S, F] arrays — identical under numpy and jax.numpy.
    """
    xp = backend_mod.xp(backend)
    feat = xp.asarray(feat)
    base = xp.asarray(base)
    lat_infl = xp.clip(feat[:, 0] - base[:, 0], 0.0, None)
    d_err = xp.clip(feat[:, 2] - base[:, 2], 0.0, None)
    d_log = xp.clip(feat[:, 3] - base[:, 3], 0.0, None)
    # evidence shrinkage: a p99/err estimate from a handful of spans is noise;
    # weight by n/(n+k) using the span counts carried in feature col 4 (log1p)
    d_met = xp.clip(feat[:, 6] - base[:, 6], 0.0, None)
    # api_lat_log and coverage_ratio are absolute levels (not rates): if the
    # modality was collected on only one side, its delta is the raw level and
    # would swamp every service — gate each on presence in BOTH matrices
    # (count>0 ⇒ nonzero column; Optional modalities leave all-zero columns)
    has_api = (xp.max(feat[:, 8]) > 0) & (xp.max(base[:, 8]) > 0)
    has_cov = (xp.max(feat[:, 9]) > 0) & (xp.max(base[:, 9]) > 0)
    d_api_err = xp.clip(feat[:, 7] - base[:, 7], 0.0, None) * has_api
    d_api_lat = xp.clip(feat[:, 8] - base[:, 8], 0.0, None) * has_api
    # injected faults shift executed paths, so coverage moves either way on
    # the culprit (generate_coverage drops it; a real fault may also raise
    # error-handling paths) — score the absolute shift
    d_cov = xp.abs(feat[:, 9] - base[:, 9]) * has_cov
    # level-keyed metric deltas (cols 10..12): same Δlog-level form as the
    # all-metrics column, but split by the catalog's anomaly-level groups so
    # a database fault's fd/storage movement isn't diluted by flat
    # performance families
    d_lvl = xp.sum(xp.clip(feat[:, 10:13] - base[:, 10:13], 0.0, None),
                   axis=-1)
    n = xp.expm1(feat[:, 4])
    conf = n / (n + 20.0)
    return (conf * (_W_LAT * lat_infl + _W_ERR * d_err)
            + _W_LOG * d_log + _W_MET * d_met + _W_MET * d_lvl
            + _W_API_ERR * d_api_err + _W_API_LAT * d_api_lat
            + _W_COV * d_cov)


def experiment_score(scores) -> float:
    """Experiment-level anomaly score = max service score."""
    return float(np.max(backend_mod.to_host(scores))) if np.size(scores) else 0.0


@dataclasses.dataclass
class DetectionResult:
    experiment: str
    is_anomaly_true: bool
    score: float
    ranked_services: List[str]       # descending culprit likelihood
    target_service: str

    def hit(self, k: int) -> Optional[bool]:
        if not self.target_service:
            return None  # host-level fault: no single culprit service
        return self.target_service in self.ranked_services[:k]


@dataclasses.dataclass
class EvalSummary:
    top1: float
    top3: float
    top5: float
    detection_accuracy: float
    n_rca_cases: int
    results: List[DetectionResult]


def evaluate_corpus(experiments: Sequence[Experiment],
                    backend: Optional[str] = None,
                    threshold: float = 0.35) -> EvalSummary:
    """Run detector over a 13-experiment corpus; eval vs chaos labels."""
    normal = next(e for e in experiments
                  if labels_mod.label_for(e.name).anomaly_level == "normal")
    testbed = normal.testbed
    # pinned service set: union across corpus, stable order
    services: Dict[str, None] = {}
    for e in experiments:
        if e.spans is not None:
            for s in e.spans.services:
                services.setdefault(s)
    services = tuple(services)

    base = extract_features(normal, services).x
    results: List[DetectionResult] = []
    for e in experiments:
        label = labels_mod.label_for(e.name)
        feat = extract_features(e, services).x
        scores = backend_mod.to_host(service_scores(feat, base, backend))
        order = np.argsort(-scores, kind="stable")
        results.append(DetectionResult(
            experiment=e.name,
            is_anomaly_true=label.is_anomaly,
            score=experiment_score(scores),
            ranked_services=[services[i] for i in order],
            target_service=label.target_service,
        ))

    det_correct = sum((r.score > threshold) == r.is_anomaly_true for r in results)
    rca = [r for r in results if r.is_anomaly_true and r.target_service]
    def rate(k: int) -> float:
        return (sum(bool(r.hit(k)) for r in rca) / len(rca)) if rca else 0.0
    return EvalSummary(top1=rate(1), top3=rate(3), top5=rate(5),
                       detection_accuracy=det_correct / len(results),
                       n_rca_cases=len(rca), results=results)


def per_level_breakdown(summary: EvalSummary) -> Dict[str, Dict[str, float]]:
    """Top-1/top-3 hit-rates split by anomaly level (performance/service/
    database/code) — the granularity of the reference's fault taxonomy."""
    out: Dict[str, Dict[str, float]] = {}
    for level in ("performance", "service", "database", "code"):
        rs = [r for r in summary.results
              if r.is_anomaly_true and r.target_service
              and labels_mod.label_for(r.experiment).anomaly_level == level]
        if not rs:
            continue
        out[level] = {
            "n": len(rs),
            "top1": sum(bool(r.hit(1)) for r in rs) / len(rs),
            "top3": sum(bool(r.hit(3)) for r in rs) / len(rs),
        }
    return out
