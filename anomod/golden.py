"""Golden run over the REAL reference dataset trees.

The shipped checkout's payloads are mostly git-LFS pointer stubs, but not
all of it: both coverage trees are real content (SN_data/coverage_data —
8.5k gcov text files; TT_data/coverage_report — 27.5k JaCoCo xml/html
artifacts), plus a handful of SN log/metric files.  This module is the
committed evidence that the loaders and the coverage-modality detector run
over the ACTUAL dataset, not only its synthetic shadow:

  1. :func:`scan_tree` — the loadability census: per modality, how many
     files are real vs LFS-stubbed, and which experiments' artifacts the
     typed loaders actually parse (synth fallback disabled).
  2. :func:`coverage_signal` — the coverage-modality detector on real
     data: artifact-absence fingerprinting + blast-discounted coverage
     -ratio deltas + producer triangulation, vs the normal-baseline run —
     the real-data counterpart of the ``coverage_ratio`` feature in
     anomod.detect (detect.py:116-124).
  3. :func:`log_signal` — the log-modality detector on the real
     summary.txt error/warn/line counts (collect_log.sh:101-137).

``anomod golden`` prints the full report as JSON (``--markdown`` for the
docs body); docs/GOLDEN_REPORT.md carries the committed run, pinned by
tests/test_golden.py (which re-runs the scan against /root/reference and
asserts the stable fields match).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional

from anomod import labels as labels_mod
from anomod.config import Config, get_config
from anomod.io.lfs import is_lfs_pointer

_MODALITY_SUBDIRS = {
    "SN": ("log_data", "metric_data", "trace_data", "api_responses",
           "coverage_data"),
    "TT": ("log_data", "metric_data", "trace_data", "api_responses",
           "coverage_data", "coverage_report"),
}


def _count_files(base: Path) -> Dict[str, int]:
    files = [p for p in base.rglob("*") if p.is_file()]
    stubs = sum(1 for p in files if is_lfs_pointer(p))
    return {"n_files": len(files), "n_lfs_stubs": stubs,
            "n_real": len(files) - stubs}


def _try_load(testbed: str, modality: str, d: Path):
    """Attempt the typed load of one experiment-modality dir; None when the
    artifact is missing/stubbed (synth fallback deliberately NOT taken)."""
    from anomod.io import api as api_io
    from anomod.io import coverage as cov_io
    from anomod.io import logs as logs_io
    from anomod.io import metrics as met_io
    from anomod.io import sn_traces, tt_traces
    if modality == "traces":
        if testbed == "TT":
            art = tt_traces.find_trace_artifact(d)
            return tt_traces.load_skywalking_json(art) if art else None
        art = sn_traces.find_trace_artifact(d)
        if art is None:
            return None
        return (sn_traces.load_jaeger_json(art) if art.suffix == ".json"
                else sn_traces.load_jaeger_csv(art))
    if modality == "metrics":
        if testbed == "TT":
            art = met_io.find_tt_metric_artifact(d)
            return met_io.load_tt_metric_csv(art) if art else None
        return met_io.load_sn_metric_dir(d)
    if modality == "logs":
        loader = (logs_io.load_tt_log_dir if testbed == "TT"
                  else logs_io.load_sn_log_dir)
        batch, _ = loader(d)
        # a LogBatch built from zero-line stub parses is NOT real content;
        # this criterion must live here so the standalone census agrees
        # with the _load_log_summaries preload path
        return batch if batch is not None and batch.n_lines > 0 else None
    if modality == "api":
        art = api_io.find_api_artifact(d)
        return api_io.load_api_jsonl(art) if art else None
    if modality == "coverage":
        loader = (cov_io.load_tt_coverage_report if testbed == "TT"
                  else cov_io.load_sn_coverage_dir)
        return loader(d)
    raise ValueError(modality)


def _load_coverage_batches(testbed: str, cfg: Config) -> Dict[str, object]:
    """Load every experiment's real coverage tree ONCE — shared by the
    census and the detection pass (TT's coverage_report is 27.5k files;
    parsing it twice per report would double the most expensive I/O)."""
    from anomod.io import dataset
    out: Dict[str, object] = {}
    for ed in dataset.discover(testbed, cfg):
        if "coverage" not in ed.dirs:
            continue
        cb = _try_load(testbed, "coverage", ed.dirs["coverage"])
        if cb is not None and len(cb.services):
            out[ed.name] = cb
    return out


def _load_log_summaries(testbed: str, cfg: Config) -> Dict[str, tuple]:
    """Parse every experiment's log dir ONCE — shared by the census and
    the log-signal pass (same pattern as :func:`_load_coverage_batches`).
    Returns ``{name: (line_content_is_real, summaries)}``: the census
    marks "real" on parsed LINE content (a LogBatch), while detection
    consumes the summary counts, which summary.txt carries even where the
    per-service .log payloads are LFS-stubbed."""
    from anomod.io import dataset
    from anomod.io.logs import load_sn_log_dir, load_tt_log_dir
    loader = load_tt_log_dir if testbed == "TT" else load_sn_log_dir
    out: Dict[str, tuple] = {}
    for ed in dataset.discover(testbed, cfg):
        if "logs" not in ed.dirs:
            continue
        try:
            batch, summaries = loader(ed.dirs["logs"])
        except Exception as e:
            # census contract: one unreadable tree yields an "error:" row
            # for that experiment, never an aborted report
            out[ed.name] = (f"error: {type(e).__name__}", [])
            continue
        out[ed.name] = (batch is not None and batch.n_lines > 0,
                        summaries or [])
    return out


def scan_tree(testbed: str, cfg: Optional[Config] = None,
              coverage_batches: Optional[Dict[str, object]] = None,
              log_loads: Optional[Dict[str, tuple]] = None) -> dict:
    """The loadability census for one testbed's archive tree.

    ``coverage_batches`` (from :func:`_load_coverage_batches`) and
    ``log_loads`` (from :func:`_load_log_summaries`) substitute for
    re-parsing those trees when the caller already loaded them."""
    from anomod.io import dataset
    cfg = cfg or get_config()
    root = cfg.sn_data if testbed == "SN" else cfg.tt_data
    out: dict = {"testbed": testbed, "root": str(root), "modality_files": {},
                 "experiments": {}}
    if not root.is_dir():
        out["missing"] = True
        return out
    for sub in _MODALITY_SUBDIRS[testbed]:
        base = root / sub
        if base.is_dir():
            out["modality_files"][sub] = _count_files(base)
    for ed in sorted(dataset.discover(testbed, cfg), key=lambda e: e.name):
        row = {}
        for modality, d in sorted(ed.dirs.items()):
            if modality == "coverage" and coverage_batches is not None:
                row[modality] = ("real" if ed.name in coverage_batches
                                 else "stub")
                continue
            if modality == "logs" and log_loads is not None:
                flag = log_loads.get(ed.name, (False,))[0]
                row[modality] = (flag if isinstance(flag, str)
                                 else "real" if flag else "stub")
                continue
            try:
                batch = _try_load(testbed, modality, d)
            except Exception as e:           # a real but unparseable file
                row[modality] = f"error: {type(e).__name__}"
                continue
            row[modality] = "real" if batch is not None else "stub"
        out["experiments"][ed.name] = row
    mods = out["experiments"].values()
    out["n_experiments"] = len(out["experiments"])
    out["real_loads"] = {m: sum(1 for r in mods if r.get(m) == "real")
                         for m in ("traces", "metrics", "logs", "api",
                                   "coverage")}
    return out


def _pick_normal(names) -> Optional[str]:
    """The normal-baseline experiment among ``names`` (None when absent)."""
    return next((n for n in names
                 if labels_mod.label_for(n) is not None
                 and not labels_mod.label_for(n).is_anomaly), None)


def _mark_hits(row: dict, target: str, ranked: List[str]) -> tuple:
    """Shared hit accounting for the modality scorers: annotate ``row``
    with top1/top3 hits (service names canonicalized — SN logs use
    CamelCase where the chaos labels use kebab-case) and return the
    (scored, top1, top3) increments."""
    if not ranked:
        row["no_signal"] = True
    if not (target and ranked):
        return 0, 0, 0
    want = _canon_service(target)
    got = [_canon_service(s) for s in ranked]
    row["top1_hit"] = got[0] == want
    row["top3_hit"] = want in got[:3]
    return 1, int(row["top1_hit"]), int(row["top3_hit"])


def coverage_signal(testbed: str, cfg: Optional[Config] = None,
                    batches: Optional[Dict[str, object]] = None,
                    repeat_tol: float = 0.005,
                    upstream_w: float = 1.1) -> dict:
    """Coverage-modality detection over the REAL coverage artifacts.

    Per fault experiment: per-service coverage-ratio delta vs the normal
    baseline run (services aligned by name), then culprit ranking by a
    BLAST-DISCOUNTED, PRODUCER-ATTRIBUTED score.  Raw |delta| ranking is
    confounded two ways in the real SN artifacts (the round-4 report's
    shared-top-delta artifact): (1) a fault anywhere in the compose
    pipeline starves the same downstream set by the SAME amounts — e.g.
    post-storage-service drops exactly 0.0887 under every Code_Stop —
    so a delta that repeats across other fault experiments (within
    ``repeat_tol``) is a deterministic secondary effect and is divided by
    (1 + 2·repeats); (2) a stopped service's OWN coverage never moves
    (the cumulative gcov counters already covered its paths), while its
    unique downstream consumers starve.  So when TWO OR MORE of one
    producer's callees show unique (non-repeated) starvation, they
    triangulate that producer: it inherits ``upstream_w`` x the max such
    starvation, with ``upstream_w`` > 1 because the producer cannot
    self-evidence in this data.  One uniquely starved callee alone is
    ambiguous — a killed service and a starved service look identical
    from inside their own artifact — so single-callee starvation stays
    where it is (which is exactly what lets Svc_Kill self-attribute).
    This is the real-data counterpart of the offline detector's
    ``coverage_ratio`` feature channel (anomod.detect:116-124) plus its
    dependency-attribution idea."""
    from anomod import synth
    cfg = cfg or get_config()
    if batches is None:
        batches = _load_coverage_batches(testbed, cfg)
    normal_name = _pick_normal(batches)
    out: dict = {"testbed": testbed, "n_loaded": len(batches),
                 "normal_baseline": normal_name, "experiments": []}
    if normal_name is None:
        return out
    base = batches[normal_name]
    base_ratio = dict(zip(base.services, base.service_ratio()))
    # signed per-service deltas for EVERY fault experiment up front: the
    # repeat-discount needs each delta's frequency across the others
    signed: Dict[str, Dict[str, float]] = {}
    for name, cb in batches.items():
        if name == normal_name:
            continue
        ratio = cb.service_ratio()
        signed[name] = {svc: float(ratio[si] - base_ratio[svc])
                        for si, svc in enumerate(cb.services)
                        if svc in base_ratio}
    callees_of: Dict[str, List[str]] = {}
    try:
        for a, c in synth._topology(testbed)[1]:
            callees_of.setdefault(a, []).append(c)
    except Exception:
        # triangulation degrades to delta-only ranking without topology —
        # surfaced in the record so a silent regression is visible
        pass
    out["topology_available"] = bool(callees_of)
    hits1 = hits3 = scored = 0
    max_delta = 0.0
    n_absent = 0
    n_absence_hits = 0
    for name in sorted(signed):
        label = labels_mod.label_for(name)
        if label is None:
            continue
        dmap = signed[name]
        if dmap:
            max_delta = max(max_delta, max(abs(d) for d in dmap.values()))
        disc: Dict[str, float] = {}
        unique_mover: Dict[str, bool] = {}
        for svc, d in dmap.items():
            repeats = sum(
                1 for other, od in signed.items()
                if other != name
                and abs(od.get(svc, 0.0) - d) <= repeat_tol
                and abs(od.get(svc, 0.0)) > 1e-9)
            moved = abs(d) > 1e-9
            disc[svc] = abs(d) / (1.0 + 2.0 * repeats) if moved else 0.0
            unique_mover[svc] = moved and repeats == 0
        score: Dict[str, float] = dict(disc)
        for svc in dmap:
            starve = [disc[c] for c in callees_of.get(svc, ())
                      if unique_mover.get(c) and dmap.get(c, 0.0) < 0]
            if len(starve) >= 2:
                score[svc] = max(score[svc], upstream_w * max(starve))
        # ABSENCE tier, above every delta: a service that reported
        # coverage at baseline but produced NO artifact under the fault
        # stopped executing outright — a stopped binary cannot flush its
        # gcov counters at collection time.  In the real SN tree this is
        # exactly the Code_Stop culprits' fingerprint (each is the one
        # service missing from its own experiment's coverage_data).
        absent = [svc for svc in base_ratio if svc not in dmap]
        n_absent += len(absent)
        top_disc = max(score.values(), default=0.0)
        for svc in absent:
            # among multiple absences, the higher-baseline-coverage (more
            # load-bearing) service ranks first — never the alphabetical
            # accident of the tuple sort
            score[svc] = top_disc + 1.0 + 1e-3 * base_ratio[svc]
        deltas = sorted(((s, svc) for svc, s in score.items()),
                        reverse=True)
        # a rank is only meaningful where the delta plane is non-zero:
        # zero-signal experiments must not score, or ties would credit and
        # deny hits by the sort's alphabetical accident
        ranked = [svc for s, svc in deltas if s > 1e-9]
        target = label.target_service
        row = {"experiment": name, "target": target,
               "n_services_aligned": len(dmap),
               "top3": [
                   dict({"service": svc, "score": round(s, 4),
                         "abs_delta": round(abs(dmap.get(svc, 0.0)), 4)},
                        **({"absent": True} if svc in absent else {}))
                   for s, svc in deltas[:3]]}
        ds, d1, d3 = _mark_hits(row, target, ranked)
        scored += ds
        hits1 += d1
        hits3 += d3
        if d1 and row["top3"] and row["top3"][0].get("absent"):
            n_absence_hits += 1
        out["experiments"].append(row)
    out["scored"] = scored
    out["top1"] = round(hits1 / scored, 3) if scored else None
    out["top3"] = round(hits3 / scored, 3) if scored else None
    # An all-zero delta plane means the ARTIFACTS carry no per-experiment
    # signal (the shipped TT coverage-summary.txt files are byte-identical
    # across experiments), not that the detector failed — distinguish the
    # two in the committed record.
    out["max_abs_delta"] = round(max_delta, 6)
    out["n_absent_artifacts"] = n_absent
    out["n_absence_top1_hits"] = n_absence_hits
    # absence is signal too (an experiment could carry ONLY the missing
    # -artifact fingerprint and still score)
    out["signal_present"] = max_delta > 1e-9 or n_absent > 0
    return out


def _canon_service(name: str) -> str:
    """SN logs name services in CamelCase (``MediaService``) while the
    chaos labels use kebab-case (``media-service``); canonicalize both for
    target matching (collect_log.sh's SERVICES list vs the label
    taxonomy)."""
    import re
    s = re.sub(r"(?<!^)(?=[A-Z])", "-", name).lower()
    return s.strip("-")


def log_signal(testbed: str, cfg: Optional[Config] = None,
               log_loads: Optional[Dict[str, tuple]] = None) -> dict:
    """Log-modality detection over the REAL log artifacts.

    Per fault experiment with real (non-stub) logs: per-service error-rate
    and warn-rate deltas vs the normal-baseline run (services aligned by
    name), culprit ranking by the error-rate delta with warn-rate and
    log-VOLUME shift (|ln(lines_exp / lines_base)|) as tiebreak channels —
    volume is what a kill/stop fault moves when it never writes an error
    line (the service just goes quiet).  All three come from the same
    per-service error/warn/line counts the reference's collector writes
    into ``summary.txt`` (collect_log.sh:101-137); the offline detector's
    ``log_err_rate`` feature is the synthetic counterpart
    (anomod.detect FEATURES).  ``log_loads`` (from
    :func:`_load_log_summaries`) substitutes for re-parsing the log
    trees."""
    import math

    cfg = cfg or get_config()
    if log_loads is None:
        log_loads = _load_log_summaries(testbed, cfg)
    rates: Dict[str, Dict[str, tuple]] = {}
    for name, (_, summaries) in log_loads.items():
        by_svc: Dict[str, List[int]] = {}
        for s in summaries:
            agg = by_svc.setdefault(s.service, [0, 0, 0])
            agg[0] += s.n_lines
            agg[1] += s.n_error
            agg[2] += s.n_warn
        svc_rates = {
            svc: (err / n, warn / n, n)
            for svc, (n, err, warn) in by_svc.items() if n > 0}
        # an experiment whose every parsed file is empty (LFS stub dirs
        # with zero-byte logs) has no real log content — do not count it
        # as loaded, or "loaded" overstates the census
        if svc_rates:
            rates[name] = svc_rates
    normal_name = _pick_normal(rates)
    out: dict = {"testbed": testbed, "n_loaded": len(rates),
                 "normal_baseline": normal_name, "experiments": []}
    if normal_name is None:
        return out
    base = rates[normal_name]
    hits1 = hits3 = scored = 0
    max_delta = 0.0
    max_vol = 0.0
    for name, svc_rates in sorted(rates.items()):
        label = labels_mod.label_for(name)
        if name == normal_name or label is None:
            continue
        deltas = []
        for svc, (err, warn, n) in svc_rates.items():
            if svc in base:
                b_err, b_warn, b_n = base[svc]
                dv = abs(math.log(n / b_n))
                deltas.append((abs(err - b_err), abs(warn - b_warn), dv,
                               svc))
        deltas.sort(reverse=True)
        if deltas:
            max_delta = max(max_delta, deltas[0][0])
            max_vol = max(max_vol, max(d[2] for d in deltas))
        # Volume as evidence, two regimes.  The SN collector gathers the
        # FULL cumulative log history per experiment (summary.txt header:
        # unbounded time range), so most services' line counts are
        # bit-identical to the baseline.  When nearly everything is
        # exactly unchanged (<= 3 movers), the baseline is deterministic
        # and ANY mover is significant — a killed service's file goes
        # quiet, a ~0.2% dip at exactly one service.  When volume moves
        # broadly, counts jitter and only a >10% shift is evidence.
        n_movers = sum(1 for de, dw, dv, svc in deltas if dv > 1e-12)
        vol_eps = 1e-12 if n_movers <= 3 else 0.1
        ranked = [svc for de, dw, dv, svc in deltas
                  if de > 1e-12 or dw > 1e-12 or dv > vol_eps]
        # ABSENCE tier, above every delta (mirrors coverage_signal): a
        # service that logged at baseline but has NO (or zero-line) rows
        # under the fault went silent outright — the strongest kill
        # fingerprint a non-cumulative collector would produce.  Among
        # multiple absences the higher-volume baseline service ranks
        # first (never the sort's alphabetical accident).
        absent = sorted((svc for svc in base if svc not in svc_rates),
                        key=lambda svc: -base[svc][2])
        ranked = absent + ranked
        target = label.target_service
        row = {"experiment": name, "target": target,
               "n_services_aligned": len(deltas),
               "top3": ([{"service": svc, "absent": True}
                         for svc in absent[:3]]
                        + [{"service": svc, "err_delta": round(de, 5),
                            "warn_delta": round(dw, 5),
                            "vol_shift": round(dv, 6)}
                           for de, dw, dv, svc in deltas[:3]])[:3]}
        ds, d1, d3 = _mark_hits(row, target, ranked)
        scored += ds
        hits1 += d1
        hits3 += d3
        out["experiments"].append(row)
    out["scored"] = scored
    out["top1"] = round(hits1 / scored, 3) if scored else None
    out["top3"] = round(hits3 / scored, 3) if scored else None
    out["max_abs_err_delta"] = round(max_delta, 6)
    out["max_abs_vol_shift"] = round(max_vol, 6)
    # hits can ride EITHER channel (the Svc_Kill hits are volume-only),
    # so signal presence must cover both or the record contradicts itself
    out["signal_present"] = max_delta > 1e-12 or max_vol > 1e-12
    return out


def golden_report(cfg: Optional[Config] = None) -> dict:
    """The full committed golden run: census + real-data coverage and
    log-modality detection for both testbeds (coverage trees parsed once
    each)."""
    cfg = cfg or get_config()
    out: dict = {"scan": {}, "coverage_detection": {}, "log_detection": {}}
    for tb in ("SN", "TT"):
        batches = _load_coverage_batches(tb, cfg)
        log_loads = _load_log_summaries(tb, cfg)
        out["scan"][tb] = scan_tree(tb, cfg, coverage_batches=batches,
                                    log_loads=log_loads)
        out["coverage_detection"][tb] = coverage_signal(tb, cfg,
                                                        batches=batches)
        out["log_detection"][tb] = log_signal(tb, cfg, log_loads=log_loads)
    return out


def format_markdown(report: dict) -> str:
    """docs/GOLDEN_REPORT.md body from a report dict."""
    lines: List[str] = [
        "# Golden run over the real reference dataset",
        "",
        "Generated by `anomod golden` against the shipped checkout "
        "(`/root/reference`); regenerate with "
        "`ANOMOD_PLATFORM=cpu anomod golden --markdown`.  Pinned by "
        "`tests/test_golden.py`.",
        "",
        "## Loadability census (typed loaders, synth fallback disabled)",
        "",
        "The logs column counts experiments whose per-LINE log content "
        "parses (a non-empty LogBatch; zero-line parses of LFS-stub dirs "
        "were miscounted as real in earlier report revisions).  "
        "Summary-level log content (summary.txt error/warn/line counts) "
        "is censused and scored separately in the log-modality section "
        "below: " + "; ".join(
            "{} line-content loads={}, summary loads={}".format(
                tb,
                report["scan"][tb].get("real_loads", {}).get("logs", 0),
                report.get("log_detection", {}).get(tb, {})
                      .get("n_loaded", 0))
            for tb in report.get("scan", {})) + ".",
        "",
    ]
    for tb, scan in report["scan"].items():
        lines += [f"### {tb}_data", "",
                  "| modality dir | files | LFS stubs | real |",
                  "|---|---|---|---|"]
        for sub, c in scan.get("modality_files", {}).items():
            lines.append(f"| {sub} | {c['n_files']} | {c['n_lfs_stubs']} "
                         f"| {c['n_real']} |")
        rl = scan.get("real_loads", {})
        lines += ["",
                  f"{scan.get('n_experiments', 0)} experiments discovered; "
                  f"real (non-stub) loads per modality: "
                  + ", ".join(f"{m}={n}" for m, n in rl.items()) + ".", ""]
    lines += ["## Coverage-modality detection on real artifacts",
              "",
              "Ranking is three-tiered (coverage_signal): (1) a service "
              "present in the baseline but missing from the fault run's "
              "coverage tree outranks everything — a stopped binary "
              "cannot flush its gcov counters, so artifact ABSENCE is "
              "the stop-fault fingerprint; (2) deltas that repeat "
              "identically across other fault experiments are "
              "deterministic pipeline blast and are discounted; (3) two "
              "or more uniquely starved callees triangulate their "
              "shared producer through the call topology.",
              ""]
    for tb, cov in report["coverage_detection"].items():
        lines += [f"### {tb}",
                  "",
                  f"- experiments with loadable real coverage: "
                  f"{cov['n_loaded']}",
                  f"- normal baseline: `{cov.get('normal_baseline')}`",
                  f"- culprit ranking (absence tier + blast-discounted "
                  f"deltas + producer triangulation): "
                  f"top-1 {cov.get('top1')}, top-3 {cov.get('top3')} over "
                  f"{cov.get('scored', 0)} scored faults"
                  + (f"; {cov.get('n_absence_top1_hits', 0)} culprits "
                     f"identified by artifact absence"
                     if cov.get("n_absence_top1_hits") else ""),
                  f"- max |delta| anywhere: {cov.get('max_abs_delta')} "
                  + ("(real per-experiment signal present)"
                     if cov.get("signal_present") else
                     "(the shipped artifacts are IDENTICAL across "
                     "experiments — the modality carries no culprit "
                     "signal in this dataset, which the synthetic "
                     "corpus deliberately does not replicate)"), ""]
        for row in cov.get("experiments", []):
            t3 = ", ".join(
                f"{e['service']} (ABSENT)" if e.get("absent")
                else f"{e['service']} ({e['abs_delta']})"
                for e in row["top3"])
            mark = ("no signal (unscored)" if row.get("no_signal")
                    else "hit" if row.get("top1_hit")
                    else "top3" if row.get("top3_hit") else "miss")
            lines.append(f"- `{row['experiment']}` target "
                         f"`{row['target']}` -> {mark}; largest deltas: "
                         f"{t3}")
        lines.append("")
    lines += ["## Log-modality detection on real artifacts",
              "",
              "Per-service error/warn RATES (errors / lines, the "
              "collect_log.sh:101-137 summary counts normalized by "
              "volume) plus the log-VOLUME shift |ln(lines/baseline)|, "
              "deltas vs the normal baseline.  Ranking is two-tiered: a "
              "service that logged at baseline but has NO countable row "
              "under the fault (summary.txt records no log file) "
              "outranks everything — going silent is the stop/kill "
              "fingerprint — then error-rate delta with warn-rate and "
              "volume as tiebreak channels.",
              ""]
    # the two dataset findings are emitted only when THIS run's rows
    # exhibit them — a regeneration after `git lfs pull` (or against a
    # different checkout) must not carry stale narrative
    sn_rows = report.get("log_detection", {}).get("SN", {}) \
                    .get("experiments", [])
    sink_misses = [r for r in sn_rows
                   if r.get("top1_hit") is False and r["top3"]
                   and r["top3"][0]["service"] == "ComposePostService"
                   and r["top3"][0].get("err_delta", 0) > 0]
    vol_hits = [r for r in sn_rows
                if r.get("top1_hit") and r["top3"]
                and r["top3"][0].get("err_delta", 1) == 0
                and r["top3"][0].get("vol_shift", 0) > 0]
    if vol_hits or sink_misses:
        finding_bits = []
        if vol_hits:
            finding_bits.append(
                "the SN collector gathers the FULL cumulative log history "
                "per experiment (summary.txt header: unbounded time "
                "range), so most services' counts are bit-identical "
                "across experiments and only accumulating effects "
                "register — which also means a lone mover in an "
                "otherwise frozen plane is significant (the "
                f"{len(vol_hits)} volume-only hits below ride a small "
                "volume dip at exactly the killed service)")
        if sink_misses:
            finding_bits.append(
                f"{len(sink_misses)} faults log their errors at "
                "`ComposePostService` — the orchestrator CALLING the "
                "faulted service — so summary-level log evidence "
                "localizes the propagation SINK, one call-graph hop "
                "downstream of the culprit; the per-line log text that "
                "could resolve the hop is LFS-stubbed in the shipped "
                "checkout")
        lines += ["Dataset findings exhibited by this run: "
                  + "; ".join(finding_bits) + ".", ""]
    for tb, lg in report.get("log_detection", {}).items():
        lines += [f"### {tb}",
                  "",
                  f"- experiments with real (non-stub) logs: "
                  f"{lg['n_loaded']}",
                  f"- normal baseline: `{lg.get('normal_baseline')}`",
                  f"- culprit ranking (absence tier + error-rate "
                  f"delta): top-1 {lg.get('top1')}, top-3 "
                  f"{lg.get('top3')} over {lg.get('scored', 0)} "
                  f"scored faults",
                  f"- max |err-rate delta| anywhere: "
                  f"{lg.get('max_abs_err_delta')}", ""]
        for row in lg.get("experiments", []):
            t3 = ", ".join(
                f"{e['service']} (ABSENT)" if e.get("absent")
                else f"{e['service']} (err {e['err_delta']}, "
                     f"vol {e['vol_shift']})" for e in row["top3"])
            mark = ("no signal (unscored)" if row.get("no_signal")
                    else "hit" if row.get("top1_hit")
                    else "top3" if row.get("top3_hit") else "miss")
            lines.append(f"- `{row['experiment']}` target "
                         f"`{row['target']}` -> {mark}; largest deltas: "
                         f"{t3}")
        lines.append("")
    return "\n".join(lines)
