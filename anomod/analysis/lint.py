"""Linter core: file walking, suppression syntax, baseline semantics.

The framework pieces live here; the contract knowledge lives in
``anomod.analysis.rules`` (AST rule families) and
``anomod.analysis.parity`` (the import-free parity-surface audit).

Suppression contract
--------------------

A finding is suppressed by a directive on ITS line, or by a directive-
only line directly above the statement it blesses (the suppression
covers that one statement — a compound statement's body included)::

    val = time.time()  # anomod-lint: disable=D101 — forensic timestamp

    # anomod-lint: disable=S301 — fused gather reads through pool.gather_window
    return reps[0]._runner.pool.gather_window(slots, cols)

``disable-file=RULE`` anywhere in the file suppresses the rule for the
whole file.  The reason (after ``—``, ``--`` or ``:``) is REQUIRED:
a bare disable is itself a finding (``LINT000``) that cannot be
suppressed — the directive's job is to leave a reviewable why behind.

Baseline contract
-----------------

``scripts/lint_baseline.json`` holds finding keys accepted at gate
time.  The gate fails only on findings NOT in the baseline, so adopting
a new rule never blocks the tree — but the baseline may only shrink:
a stale entry (baselined finding that no longer fires) is reported so
``--update-baseline`` ratchets it out.  This repo's baseline ships
EMPTY: every finding of the first full run was fixed in place or
carries a reasoned inline suppression.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

#: rule-id grammar (also the directive parser's token shape)
_RULE_ID = re.compile(r"^(LINT|[DESPLC])\d{3}$")

_DIRECTIVE_HINT = re.compile(r"#\s*anomod-lint:")
_DIRECTIVE = re.compile(
    r"#\s*anomod-lint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Z]+\d{3}(?:\s*,\s*[A-Z]+\d{3})*)"
    r"(?:\s*(?:—|--|:)\s*(?P<reason>.*?))?\s*$")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One enforced contract (docs/CONTRACTS.md renders this table)."""
    id: str
    family: str
    synopsis: str
    #: which shipped bug (or prose contract) motivated mechanizing it
    motivation: str


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative posix path
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    @property
    def key(self) -> str:
        """Baseline identity.  Deliberately line-numbered: a baselined
        finding that MOVES re-fires, which is the conservative side."""
        return f"{self.rule}|{self.path}|{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _comment_lines(source: str):
    """(line_number, comment_text) for every REAL comment token.

    Tokenizing (not splitlines) is what keeps directive-looking text
    inside string literals and docstrings — e.g. a doc example of the
    suppression syntax — from being parsed as a live directive: a
    malformed one would raise an unsuppressable LINT000 with no escape
    but rewriting the string.  Falls back to a whole-line scan only
    when the source does not tokenize (it already parsed as AST, so
    this is vestigial caution)."""
    import io
    import tokenize
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                # standalone == nothing but whitespace before the `#`
                standalone = not tok.line[:tok.start[1]].strip()
                yield tok.start[0], tok.string, standalone
    except (tokenize.TokenError, IndentationError):
        for i, text in enumerate(source.splitlines(), start=1):
            yield i, text, text.strip().startswith("#")


class Suppressions:
    """Parsed ``anomod-lint`` directives of one file."""

    def __init__(self, source: str, path: str):
        self.by_line: Dict[int, Tuple[Tuple[str, ...], str]] = {}
        self.standalone: Dict[int, Tuple[Tuple[str, ...], str]] = {}
        self.file_wide: Dict[str, str] = {}
        self.errors: List[Finding] = []
        for i, text, standalone in _comment_lines(source):
            if not _DIRECTIVE_HINT.search(text):
                continue
            m = _DIRECTIVE.search(text)
            if not m:
                self.errors.append(Finding(
                    "LINT000", path, i,
                    "malformed suppression directive — syntax: "
                    "# anomod-" "lint: disable=D101 — reason"))
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(",")
                          if r.strip())
            reason = (m.group("reason") or "").strip()
            bad = [r for r in rules if not _RULE_ID.match(r)]
            if bad or not rules:
                self.errors.append(Finding(
                    "LINT000", path, i,
                    f"malformed suppression (unknown rule id "
                    f"{', '.join(bad) or '<none>'}) — syntax: "
                    "# anomod-" "lint: disable=D101 — reason"))
                continue
            if not reason:
                self.errors.append(Finding(
                    "LINT000", path, i,
                    "suppression without a reason — write "
                    "# anomod-" "lint: disable="
                    f"{','.join(rules)} — <why this exception is safe>"))
                continue
            if m.group("scope"):
                for r in rules:
                    self.file_wide[r] = reason
            else:
                self.by_line[i] = (rules, reason)
                # a directive-ONLY line suppresses the statement below
                # it; ModuleContext widens this to the statement's full
                # extent once the tree is parsed
                if standalone:
                    self.standalone[i] = (rules, reason)

    def match(self, rule: str, line: int) -> Optional[str]:
        """The reason when ``rule`` at ``line`` is suppressed."""
        if rule in self.file_wide:
            return self.file_wide[rule]
        got = self.by_line.get(line)
        if got and rule in got[0]:
            return got[1]
        return None


class ModuleContext:
    """Everything a rule needs about one file: the parsed tree (with
    parent links), the source, the path that decides rule scoping, and
    the env-contract coverage corpus."""

    def __init__(self, source: str, path: str, corpus: str = ""):
        self.source = source
        self.path = path.replace("\\", "/")
        self.corpus = corpus
        self.tree = ast.parse(source)
        # ONE tree traversal: node list (every rule iterates this
        # instead of re-walking — 8 walks/file made the repo lint take
        # seconds), parent links, statement extents and import aliases
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.nodes: List[ast.AST] = [self.tree]
        #: head-alias -> real module name ("np" -> "numpy",
        #: "_time" -> "time", "pc" -> "time.perf_counter")
        self.imports: Dict[str, str] = {}
        ends: Dict[int, int] = {}
        i = 0
        while i < len(self.nodes):
            node = self.nodes[i]
            i += 1
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                self.nodes.append(child)
            if isinstance(node, ast.stmt):
                end = getattr(node, "end_lineno", None) or node.lineno
                ends[node.lineno] = max(ends.get(node.lineno,
                                                 node.lineno), end)
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.imports[a.asname] = a.name
                    else:
                        # `import a.b` binds the ROOT name `a`, and
                        # that name refers to module `a` — mapping it
                        # to "a.b" would make resolve() spell
                        # a.b.<attr> as "a.b.b.<attr>" and silently
                        # skip the D103/E2xx match tables
                        root = a.name.split(".")[0]
                        self.imports[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        self.suppressions = Suppressions(source, self.path)
        # widen each directive-only line to the full extent of the
        # statement starting below it (a compound statement's body
        # included): the directive blesses ONE reviewable construct,
        # e.g. the engine's fused-gather branch
        for ln0, entry in self.suppressions.standalone.items():
            for ln in range(ln0 + 1, ends.get(ln0 + 1, ln0 + 1) + 1):
                self.suppressions.by_line.setdefault(ln, entry)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of an expression with the head import-alias
        resolved ("np.random.default_rng" -> "numpy.random.default_rng");
        None when the head is not a known module or builtin."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.imports.get(node.id)
        if head is None:
            if parts:            # obj.attr where obj is not a module
                return None
            head = node.id       # bare name: builtin candidate
        parts.append(head)
        return ".".join(reversed(parts))


# ---------------------------------------------------------------------------
# repo walking
# ---------------------------------------------------------------------------

def repo_root() -> Path:
    """This checkout's root (anomod/analysis/lint.py -> repo)."""
    return Path(__file__).resolve().parents[2]


def scan_files(root: Path) -> List[Path]:
    """The lint scan set: the package, the bench driver and the CI
    scripts.  tests/ is deliberately excluded — tests/lint_fixtures/
    holds must-trip corpora."""
    files = []
    bench = root / "bench.py"
    if bench.is_file():
        files.append(bench)
    files += sorted((root / "anomod").rglob("*.py"))
    files += sorted((root / "scripts").glob("*.py"))
    return [p for p in files if p.is_file()]


def env_corpus(root: Path) -> str:
    """The env-contract coverage corpus — same definition as
    ``scripts/check_env_contract.py``: the Config module plus every
    markdown doc."""
    parts = []
    for p in [root / "anomod" / "config.py", root / "README.md",
              *sorted((root / "docs").glob("*.md"))]:
        if p.is_file():
            parts.append(p.read_text(errors="replace"))
    return "\n".join(parts)


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------

def lint_source(source: str, path: str, corpus: str = "") -> List[Finding]:
    """Lint one source blob under the scoping identity ``path`` (tests
    hand fixture files a pretend canonical/seam/locked path).  Returns
    EVERY finding; suppressed ones carry ``suppressed=True`` and the
    directive's reason."""
    from anomod.analysis import rules as _rules
    ctx = ModuleContext(source, path, corpus)
    raw: List[Finding] = []
    seen: set = set()
    for rule_fn in _rules.ALL_CHECKS:
        for f in rule_fn(ctx):
            if f.key not in seen:       # one finding per (rule, line)
                seen.add(f.key)
                raw.append(f)
    out = list(ctx.suppressions.errors)     # LINT000: never suppressible
    for f in raw:
        reason = ctx.suppressions.match(f.rule, f.line)
        if reason is not None:
            f = dataclasses.replace(f, suppressed=True, reason=reason)
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_repo(root: Optional[Path] = None,
              paths: Optional[Iterable[Path]] = None) -> List[Finding]:
    """Lint the whole scan set (or an explicit file list)."""
    root = Path(root) if root is not None else repo_root()
    corpus = env_corpus(root)
    findings: List[Finding] = []
    for p in (list(paths) if paths is not None else scan_files(root)):
        rel = p.resolve().relative_to(root.resolve()).as_posix() \
            if p.resolve().is_relative_to(root.resolve()) else p.as_posix()
        try:
            findings.extend(lint_source(
                p.read_text(errors="replace"), rel, corpus))
        except SyntaxError as e:
            findings.append(Finding(
                "LINT000", rel, int(e.lineno or 0),
                f"file does not parse: {e.msg}"))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

BASELINE_NAME = "lint_baseline.json"


def baseline_path(root: Optional[Path] = None) -> Path:
    return (Path(root) if root is not None else repo_root()) \
        / "scripts" / BASELINE_NAME


def load_baseline(path) -> List[str]:
    p = Path(path)
    if not p.is_file():
        return []
    doc = json.loads(p.read_text())
    keys = doc.get("findings", [])
    if not isinstance(keys, list) or \
            not all(isinstance(k, str) for k in keys):
        raise ValueError(f"malformed lint baseline: {p}")
    return keys


def save_baseline(path, keys: Iterable[str]) -> None:
    """Write a baseline.  LINT000 keys are dropped: a malformed or
    reasonless suppression can only be fixed, never ridden."""
    Path(path).write_text(json.dumps(
        {"version": 1,
         "findings": sorted({k for k in keys
                             if not k.startswith("LINT000|")})},
        indent=2) + "\n")


def summarize(findings: List[Finding],
              baseline: Iterable[str] = ()) -> dict:
    """The gate verdict: new findings fail; baselined ones ride (and
    only shrink); suppressed ones are counted, not failed."""
    base = set(baseline)
    active = [f for f in findings if not f.suppressed]
    # LINT000 (reasonless/malformed suppression) is never baselinable:
    # a baseline entry for it would let `--update-baseline` launder the
    # exact silent-disable hole the rule exists to close
    new = [f for f in active
           if f.key not in base or f.rule == "LINT000"]
    known = [f for f in active
             if f.key in base and f.rule != "LINT000"]
    stale = sorted(base - {f.key for f in active})
    return {
        "check": "anomod_lint",
        "rules": len(RULES),
        "findings": len(new),
        "baselined": len(known),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baseline_size": len(base),
        "stale_baseline": stale,
        "status": "ok" if not new else "contract-violations",
        "new": [f.render() for f in new],
    }


def run_gate(root: Optional[Path] = None, include_parity: bool = True,
             baseline_file=None) -> Tuple[dict, List[Finding]]:
    """THE gate composition — lint + parity audit + baseline compare —
    in one place, shared by ``anomod lint`` (cli.py), the CI gate
    (scripts/check_contracts.py) and the ``anomod validate`` status
    block, so the three surfaces can never report different verdicts
    for the same tree.  Returns ``(summary_doc, findings)``."""
    root = Path(root) if root is not None else repo_root()
    findings = lint_repo(root)
    if include_parity:
        from anomod.analysis.parity import run_parity_audit
        findings = findings + run_parity_audit(root)
    bpath = baseline_file if baseline_file is not None \
        else baseline_path(root)
    return summarize(findings, load_baseline(bpath)), findings


def status_block(root: Optional[Path] = None) -> dict:
    """The ``anomod validate`` contract-health block: rule inventory,
    live finding counts and baseline size, plus the parity-surface
    verdict — contract health next to the native/cache blocks."""
    doc, _ = run_gate(root)
    return {"rules": doc["rules"], "findings": doc["findings"],
            "baselined": doc["baselined"],
            "suppressed": doc["suppressed"],
            "baseline_size": doc["baseline_size"],
            "status": doc["status"]}


# ---------------------------------------------------------------------------
# the rule catalog (ONE place; docs/CONTRACTS.md and `anomod lint
# --rules` render it)
# ---------------------------------------------------------------------------

RULES: Dict[str, Rule] = {r.id: r for r in [
    Rule("LINT000", "lint",
         "malformed or reasonless suppression directive",
         "a silent disable is the vigilance hole this plane replaces"),
    Rule("D101", "determinism",
         "wall-clock/stall call (time.time, monotonic, sleep, "
         "datetime.now) in a canonical-plane module",
         "the flight journal and audit replay (PR 9) require every "
         "canonical decision to be a function of seed+config alone"),
    Rule("D102", "determinism",
         "time.perf_counter outside wall-leg form (t-var assign or "
         "`... - t0` delta feeding a variant wall field)",
         "wall legs are the declared variant tier (PR 7's five-leg "
         "decomposition); any other clock use can leak into decisions"),
    Rule("D103", "determinism",
         "unseeded or global-state RNG (np.random.default_rng(), "
         "legacy np.random.*, stdlib random.*) in a canonical module",
         "PR 6 pinned RCA verdicts byte-identical across shard counts "
         "only because every sampler is keyed by (seed, tenant, window)"),
    Rule("D104", "determinism",
         "id() call in a canonical module (memory-address keys differ "
         "across processes and replays)",
         "an id()-keyed dict iterates in address order — the same "
         "failure shape as the PR-5 torn-scrape bug: invisible locally"),
    Rule("D105", "determinism",
         "set iteration feeding ordered output (for/list/tuple/"
         "enumerate/join over a set) without sorted()",
         "set order varies across processes; the shard partition and "
         "every journal digest assume stable iteration order"),
    Rule("E201", "env-contract",
         "ANOMOD_* env read that is neither Config-validated "
         "(anomod/config.py) nor documented (README/docs)",
         "PR 3's check_env_contract found 10 rotted knobs; this is its "
         "AST-level upgrade (catches aliased reads)"),
    Rule("E202", "env-contract",
         "dynamic ANOMOD_* env read (f-string/concat key) — "
         "statically unresolvable, must route through anomod.config",
         "the grep gate could not see os.environ[f'ANOMOD_{name}'] — "
         "a documented false negative of the PR-3 scanner"),
    Rule("S301", "seam",
         "pool-plane internals (._slot/._slots/._runner) touched "
         "outside the seam modules (replay.py, serve/batcher.py)",
         "PR 8's pool.put(None, ...) broadcast corruption: every "
         "bypass of the get_state/set_state/gather seam is one bug "
         "away from fleet-wide state corruption"),
    Rule("S302", "seam",
         "gather-side return aliasing a pool plane row (subscript on "
         "agg/hist without .copy()/np.asarray)",
         "the gather contract is ALWAYS-COPY (PR 8): an aliased row "
         "mutates under the next scatter fold — the PR-4 scratch-"
         "aliasing bug's state-pool twin"),
    Rule("P401", "parity",
         "ServeReport field neither in SHARD_VARIANT_REPORT_FIELDS "
         "nor named by any test",
         "a new report field silently widening the variant surface "
         "is how the N-shard==1-shard pin rots"),
    Rule("P402", "parity",
         "stale SHARD_VARIANT_REPORT_FIELDS entry (names no "
         "ServeReport field)",
         "a stale exclusion hides the day a real field takes the name"),
    Rule("P403", "parity",
         "flight tick-record key outside the declared contract "
         "(PLANES + FLIGHT_VARIANT_KEYS + the tick spine)",
         "an undeclared key is invisible to audit diff — decisions "
         "could diverge without the bisector ever naming them"),
    Rule("P404", "parity",
         "declared flight plane/variant key missing from the tick "
         "record",
         "every record carries every tier (the self-describing-shape "
         "contract the variant-key tests pin)"),
    Rule("C601", "commit-barrier",
         "read of deferred-commit state (tenant detectors/replays, "
         "RCA queue, report/flight/perf/census/policy publishers) "
         "between a deferred dispatch and _commit_deferred()",
         "the async serve tick (ANOMOD_SERVE_ASYNC_COMMIT) keeps byte "
         "parity only because nothing reads scored state while folds "
         "are in flight — one read in the window is a silent parity "
         "break the journal diff would catch hours later"),
    Rule("L501", "lock",
         "shared-state mutation outside `with self._lock` in a "
         "lock-owning class (Registry/Histogram/Tracer)",
         "PR 5's torn histogram scrape: 105 corrupt scrapes in the "
         "GIL-churn hammer before samples() took one locked snapshot"),
]}
