"""AST rule families: determinism, env-contract, seam, lock discipline.

Each check is a function ``(ModuleContext) -> [Finding]`` registered in
``ALL_CHECKS``; scoping is path-based so tests can lint fixture files
under a pretend canonical/seam path.  The rule ids, synopses and
motivations live in ``anomod.analysis.lint.RULES`` (one catalog).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from anomod.analysis.envscan import env_reads
from anomod.analysis.lint import Finding, ModuleContext

# ---------------------------------------------------------------------------
# scoping — the module sets each contract governs
# ---------------------------------------------------------------------------

#: canonical-plane modules: every decision here must be a function of
#: seed+config alone (the audit-replay contract, PR 9)
def is_canonical(path: str) -> bool:
    return path.startswith("anomod/serve/") or path in (
        "anomod/replay.py", "anomod/obs/flight.py")


#: seam modules: the ONLY homes of pool-plane internals
SEAM_MODULES = ("anomod/replay.py", "anomod/serve/batcher.py")

#: lock-owning modules: classes here guard shared state with self._lock
LOCKED_MODULES = ("anomod/obs/registry.py", "anomod/utils/tracing.py")


# ---------------------------------------------------------------------------
# D1xx — determinism
# ---------------------------------------------------------------------------

#: wall-clock / wall-stall calls with no place in a canonical plane
_WALL_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.sleep", "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}

#: the wall-leg naming convention: perf_counter results live in t-vars
#: (t0/t1/t_wall/...) and flow into variant wall fields via `... - t0`
_T_VAR = re.compile(r"^_?t\d*$|^_?t_[a-z0-9_]+$")

#: seeded-RNG surface of numpy.random; anything else is the legacy
#: global-state API
_NP_RANDOM_OK = {"default_rng", "Generator", "SeedSequence", "PCG64",
                 "Philox", "BitGenerator"}


def _is_t_var(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return bool(_T_VAR.match(node.id))
    if isinstance(node, ast.Attribute):
        return bool(_T_VAR.match(node.attr))
    return False


def check_determinism(ctx: ModuleContext) -> List[Finding]:
    if not is_canonical(ctx.path):
        return []
    out: List[Finding] = []
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = ctx.resolve(node.func)
        if name is None:
            continue
        if name in _WALL_CALLS:
            out.append(Finding(
                "D101", ctx.path, node.lineno,
                f"{name}() in a canonical-plane module — decisions "
                "must be functions of seed+config (use the virtual "
                "clock / tick index)"))
        elif name == "time.perf_counter":
            parent = ctx.parents.get(node)
            ok = (isinstance(parent, ast.Assign)
                  and all(_is_t_var(t) for t in parent.targets)) or \
                 (isinstance(parent, ast.BinOp)
                  and isinstance(parent.op, ast.Sub)
                  and parent.left is node and _is_t_var(parent.right))
            if not ok:
                out.append(Finding(
                    "D102", ctx.path, node.lineno,
                    "time.perf_counter() outside wall-leg form — "
                    "assign to a t-var (t0/t_wall) or subtract one "
                    "(`... - t0`); anything else can leak the wall "
                    "clock into a canonical decision"))
        elif name == "numpy.random.default_rng":
            if not node.args and not node.keywords:
                out.append(Finding(
                    "D103", ctx.path, node.lineno,
                    "np.random.default_rng() without a seed — "
                    "canonical-plane RNG must be keyed (seed, tenant, "
                    "window) like the RCA sampler"))
        elif name.startswith("numpy.random."):
            attr = name.rsplit(".", 1)[1]
            if attr not in _NP_RANDOM_OK:
                out.append(Finding(
                    "D103", ctx.path, node.lineno,
                    f"legacy global-state RNG np.random.{attr}() — "
                    "process-global stream, not replayable; use a "
                    "seeded default_rng"))
        elif name.startswith("random."):
            out.append(Finding(
                "D103", ctx.path, node.lineno,
                f"stdlib {name}() draws from the process-global RNG — "
                "not replayable from the flight header"))
        elif name == "id":
            out.append(Finding(
                "D104", ctx.path, node.lineno,
                "id() in a canonical module — memory addresses differ "
                "across processes/replays; key by a stable identity "
                "(tenant id, slot index)"))
    out.extend(_check_set_iteration(ctx))
    return out


def _is_set_expr(ctx: ModuleContext, node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return ctx.resolve(node.func) in ("set", "frozenset")
    return False


def _check_set_iteration(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []

    def trip(node: ast.AST, how: str) -> None:
        out.append(Finding(
            "D105", ctx.path, node.lineno,
            f"set iteration feeding ordered output ({how}) — set "
            "order varies across processes; wrap in sorted()"))

    for node in ctx.nodes:
        if isinstance(node, (ast.For, ast.AsyncFor)) \
                and _is_set_expr(ctx, node.iter):
            trip(node.iter, "for-loop over a set")
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                               ast.DictComp, ast.SetComp)):
            for gen in node.generators:
                # a set-comp DRAINING a set is fine (membership only);
                # list/dict/generator comprehensions keep order
                if not isinstance(node, ast.SetComp) \
                        and _is_set_expr(ctx, gen.iter):
                    trip(gen.iter, "comprehension over a set")
        elif isinstance(node, ast.Call):
            name = ctx.resolve(node.func)
            if name in ("list", "tuple", "enumerate", "iter") \
                    and node.args and _is_set_expr(ctx, node.args[0]):
                trip(node, f"{name}(set(...))")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" and node.args \
                    and _is_set_expr(ctx, node.args[0]):
                trip(node, "str.join over a set")
    return out


# ---------------------------------------------------------------------------
# E2xx — env contract (AST upgrade of scripts/check_env_contract.py)
# ---------------------------------------------------------------------------

def check_env_contract(ctx: ModuleContext) -> List[Finding]:
    if ctx.path == "anomod/config.py":
        return []           # the contract's one legitimate home
    out: List[Finding] = []
    for read in env_reads(ctx.tree, ctx):
        if read.name is not None:
            if read.name.startswith("ANOMOD_") \
                    and read.name not in ctx.corpus:
                out.append(Finding(
                    "E201", ctx.path, read.line,
                    f"env read of {read.name} is neither in the Config "
                    "env contract (anomod/config.py) nor documented "
                    "(README.md / docs/*.md)"))
        elif read.prefix and "ANOMOD_" in read.prefix:
            out.append(Finding(
                "E202", ctx.path, read.line,
                f"dynamic ANOMOD_* env read (key built from "
                f"{read.prefix!r}...) — statically unresolvable; "
                "route it through anomod.config or name the full "
                "variable"))
    return out


# ---------------------------------------------------------------------------
# S3xx — seam discipline
# ---------------------------------------------------------------------------

#: the pool-plane private surface: a tenant slot handle, the slot
#: table, and the runner backref PooledStreamReplay reaches its pool by
_SEAM_PRIVATE = {"_slot", "_slots", "_runner"}

#: gather-side functions bound by the always-copy contract
_GATHER_FUNCS = {"gather", "gather_window", "gather_rows", "get_state"}

#: plane attributes whose rows must never leave a gather aliased
_PLANE_ATTRS = {"agg", "hist"}

#: wrappers that materialize a copy (breaking the alias)
_COPYING_CALLS = {"numpy.asarray", "numpy.array",
                  "numpy.ascontiguousarray"}


def check_seam(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    if ctx.path not in SEAM_MODULES:
        # S301: pool internals are the seam modules' business only
        for node in ctx.nodes:
            if isinstance(node, ast.Attribute) \
                    and node.attr in _SEAM_PRIVATE:
                out.append(Finding(
                    "S301", ctx.path, node.lineno,
                    f".{node.attr} touched outside the seam modules "
                    f"({', '.join(SEAM_MODULES)}) — go through "
                    "get_state/set_state/gather (the PR-8 broadcast-"
                    "corruption lesson)"))
        return out
    # S302: inside seam modules, gather-side returns must copy
    for fn in ctx.nodes:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or fn.name not in _GATHER_FUNCS:
            continue
        for ret in ast.walk(fn):
            if not isinstance(ret, ast.Return) or ret.value is None:
                continue
            for sub in ast.walk(ret.value):
                if not (isinstance(sub, ast.Subscript)
                        and isinstance(sub.value, ast.Attribute)
                        and sub.value.attr in _PLANE_ATTRS):
                    continue
                if not _has_copying_ancestor(ctx, sub, stop=ret):
                    out.append(Finding(
                        "S302", ctx.path, sub.lineno,
                        f"{fn.name}() returns a subscript of "
                        f".{sub.value.attr} without .copy()/"
                        "np.asarray — the gather seam is ALWAYS-COPY "
                        "(an aliased row mutates under the next "
                        "scatter fold)"))
    return out


def _has_copying_ancestor(ctx: ModuleContext, node: ast.AST,
                          stop: ast.AST) -> bool:
    cur = ctx.parents.get(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.Call):
            if isinstance(cur.func, ast.Attribute) \
                    and cur.func.attr == "copy":
                return True
            if ctx.resolve(cur.func) in _COPYING_CALLS:
                return True
        cur = ctx.parents.get(cur)
    return False


# ---------------------------------------------------------------------------
# L5xx — lock discipline
# ---------------------------------------------------------------------------

#: method names that mutate their receiver in place
_MUTATORS = {"append", "appendleft", "add", "clear", "extend", "insert",
             "pop", "popleft", "remove", "update", "setdefault",
             "discard"}

#: self.<attr> bases that are thread-private by construction
_THREAD_LOCAL_ATTRS = {"_tls", "_local", "_thread_local"}


def check_lock_discipline(ctx: ModuleContext) -> List[Finding]:
    if ctx.path not in LOCKED_MODULES:
        return []
    out: List[Finding] = []
    for cls in ctx.nodes:
        if not isinstance(cls, ast.ClassDef):
            continue
        if not _owns_lock(cls):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__" or fn.name.endswith("_locked"):
                # __init__ predates sharing; *_locked documents
                # caller-holds-lock (Histogram._fold_locked idiom)
                continue
            out.extend(_scan_method(ctx, cls.name, fn))
    return out


def _owns_lock(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "_lock" \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    return True
    return False


def _is_lock_with(item: ast.withitem) -> bool:
    e = item.context_expr
    return isinstance(e, ast.Attribute) and e.attr == "_lock" \
        and isinstance(e.value, ast.Name) and e.value.id == "self"


def _self_attr_of_mutation(node: ast.AST) -> Optional[str]:
    """The mutated ``self.<attr>`` name, if this node mutates one."""
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        targets = [node.target]
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        targets = [node.func.value]
    flat: List[ast.AST] = []
    for t in targets:
        # self._a, self._b = ... (and starred unpacks) mutate too
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    for t in flat:
        if isinstance(t, ast.Starred):
            t = t.value
        while isinstance(t, ast.Subscript):    # self._metrics[k] = v
            t = t.value
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self" \
                and t.attr not in _THREAD_LOCAL_ATTRS:
            return t.attr
    return None


def _scan_method(ctx: ModuleContext, cls_name: str,
                 fn: ast.FunctionDef) -> List[Finding]:
    out: List[Finding] = []

    def walk(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.With):
            inner = locked or any(_is_lock_with(i) for i in node.items)
            for child in node.body:
                walk(child, inner)
            return
        attr = _self_attr_of_mutation(node)
        if attr is not None and not locked and attr != "_lock":
            out.append(Finding(
                "L501", ctx.path, node.lineno,
                f"{cls_name}.{fn.name} mutates self.{attr} outside "
                "`with self._lock` — the PR-5 torn-scrape shape; "
                "take the lock or rename the method *_locked"))
        for child in ast.iter_child_nodes(node):
            # nested defs get their own (unlocked) analysis scope
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                walk(child, False)
            else:
                walk(child, locked)

    for stmt in fn.body:
        walk(stmt, False)
    return out


# ---------------------------------------------------------------------------
# C6xx — the deferred-commit barrier (ANOMOD_SERVE_ASYNC_COMMIT)
# ---------------------------------------------------------------------------

#: state the deferred commit's barrier tail mutates or publishes:
#: reading any of these while issued work is still in flight observes
#: PRE-commit state — the exact leak the async-parity contract forbids
_DEFER_STATE_ATTRS = {"_tenant_det", "_tenant_replay", "_rca_queue",
                      "rca_verdicts"}

#: engine methods that read or publish committed scoring state (the
#: barrier tail itself runs them AFTER the drain)
_DEFER_READ_CALLS = {"alerts_for", "report", "_perf_drain",
                     "_census_drain", "_flight_tick", "_policy_step",
                     "_rca_step"}

#: the one sanctioned barrier
_BARRIER_CALL = "_commit_deferred"


def _iter_inline(node: ast.AST):
    """Walk a statement's subtree SKIPPING nested function/lambda
    bodies — a closure defined inside the window executes later (the
    shard-worker submit idiom), so its reads are not window reads.  A
    statement that IS a def is wholly inert."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return
    stack = list(ast.iter_child_nodes(node))
    yield node
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _opens_defer_window(node: ast.AST) -> bool:
    """A dispatch issued with ``defer=True``, or ``self._deferred``
    armed with a live payload."""
    for sub in _iter_inline(node):
        if isinstance(sub, ast.Call):
            for kw in sub.keywords:
                if kw.arg == "defer" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    return True
        elif isinstance(sub, ast.Assign):
            if isinstance(sub.value, ast.Constant) \
                    and sub.value.value is None:
                continue
            for t in sub.targets:
                if isinstance(t, ast.Attribute) \
                        and t.attr == "_deferred" \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    return True
    return False


def _closes_defer_window(node: ast.AST) -> bool:
    for sub in _iter_inline(node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == _BARRIER_CALL:
            return True
    return False


def _defer_window_reads(node: ast.AST) -> List[tuple]:
    reads = []
    for sub in _iter_inline(node):
        if isinstance(sub, ast.Attribute) \
                and sub.attr in _DEFER_STATE_ATTRS \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "self" \
                and isinstance(sub.ctx, ast.Load):
            reads.append((sub.lineno, f"self.{sub.attr}"))
        elif isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _DEFER_READ_CALLS:
            reads.append((sub.lineno, f"{sub.func.attr}()"))
    return reads


def check_commit_barrier(ctx: ModuleContext) -> List[Finding]:
    """C601: inside a function that issues deferred-commit work, no
    statement between the issue and the next ``_commit_deferred()``
    barrier may read scoring-committed state.  Function-local by
    design (the window legitimately stays open across the tick
    boundary; cross-function reads are the parity tests' job) — what
    this catches is the easy regression: someone adding a report/
    flight/RCA read into the issue half of the async tail."""
    if not ctx.path.startswith("anomod/serve/"):
        return []
    out: List[Finding] = []
    for node in ctx.nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name == _BARRIER_CALL:
            continue               # the barrier's own tail reads freely
        window_open = False
        for stmt in node.body:
            if window_open:
                # barrier-first within one compound statement is the
                # legit commit-then-read pattern, so closes win ties
                if _closes_defer_window(stmt):
                    window_open = False
                else:
                    for line, what in _defer_window_reads(stmt):
                        out.append(Finding(
                            "C601", ctx.path, line,
                            f"{node.name} reads {what} between the "
                            "deferred dispatch and the commit barrier "
                            "— the result observes PRE-commit state; "
                            "move the read after _commit_deferred()"))
            if _opens_defer_window(stmt):
                window_open = True
    return out


ALL_CHECKS = (check_determinism, check_env_contract, check_seam,
              check_lock_discipline, check_commit_barrier)
