"""Parity-surface auditor (P4xx): the variant lists stay exact.

The shard-determinism and flight-journal contracts both carve the
world into a CANONICAL surface (pinned byte-identical across shard
counts, pipeline depths, residencies, recoveries) and a declared
VARIANT surface (``SHARD_VARIANT_REPORT_FIELDS``,
``FLIGHT_VARIANT_KEYS``).  The hole this audit closes: a NEW
``ServeReport`` field or flight-record key lands, someone adds it to
the variant list (or forgets a test), and the parity surface silently
narrows — nothing fails until a real divergence ships.

The audit is fully static (pure ``ast`` over the source — no jax, no
engine import), so it runs wherever the linter runs:

- every ``ServeReport`` field must be on the variant list or NAMED by
  some test under ``tests/`` (P401) — adding a field forces either a
  conscious variant declaration or a test that pins it (the canonical
  field inventory in tests/test_analysis.py is that forcing function);
- every variant entry must name a real field (P402 — a stale exclusion
  hides the day a real field takes the name);
- every key of the engine's flight tick record must be a declared
  plane, a declared variant key, or the tick spine (P403), and every
  declared plane/variant key must be present in the record (P404 —
  the every-record-carries-every-tier contract).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional, Sequence, Set, Tuple

from anomod.analysis.lint import Finding, repo_root

#: per-tick keys that are neither plane nor variant: the tick/virtual-
#: time spine audit diff compares as "clock", plus the final-record mark
FLIGHT_SPINE = ("tick", "now_s", "final")

_ENGINE = "anomod/serve/engine.py"
_FLIGHT = "anomod/obs/flight.py"


def _parse(root: Path, rel: str) -> ast.Module:
    return ast.parse((root / rel).read_text(errors="replace"))


def _tuple_assign(tree: ast.Module, name: str) -> Optional[Tuple[str, ...]]:
    """The literal value of a module-level ``NAME = ("a", "b", ...)``
    (AnnAssign or Assign)."""
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        if target == name:
            return tuple(ast.literal_eval(value))
    return None


def serve_report_fields(root: Optional[Path] = None) -> Tuple[str, ...]:
    """ServeReport's dataclass fields, read off the AST."""
    tree = _parse(root or repo_root(), _ENGINE)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ServeReport":
            return tuple(st.target.id for st in node.body
                         if isinstance(st, ast.AnnAssign)
                         and isinstance(st.target, ast.Name))
    raise ValueError(f"ServeReport not found in {_ENGINE}")


def shard_variant_fields(root: Optional[Path] = None) -> Tuple[str, ...]:
    got = _tuple_assign(_parse(root or repo_root(), _ENGINE),
                        "SHARD_VARIANT_REPORT_FIELDS")
    if got is None:
        raise ValueError(
            f"SHARD_VARIANT_REPORT_FIELDS not found in {_ENGINE}")
    return got


def flight_contract(root: Optional[Path] = None
                    ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    tree = _parse(root or repo_root(), _FLIGHT)
    planes = _tuple_assign(tree, "PLANES")
    variant = _tuple_assign(tree, "FLIGHT_VARIANT_KEYS")
    if planes is None or variant is None:
        raise ValueError(f"PLANES/FLIGHT_VARIANT_KEYS not in {_FLIGHT}")
    return planes, variant


def flight_record_keys(root: Optional[Path] = None) -> Tuple[str, ...]:
    """The keys the engine actually writes into a flight tick record:
    the ``rec = {...}`` literal plus every ``rec["k"] = ...`` in the
    SAME function — read off the AST, so the audit sees the record
    shape the moment it changes, without running an engine.

    Scoped to the one function that hands ``rec`` to ``.record(...)``
    (the FlightRecorder publish site): an unrelated local dict that
    happens to be named ``rec`` elsewhere in engine.py must neither
    pollute the audited key set (spurious P403) nor satisfy P404 for a
    plane the real tick record no longer carries."""
    tree = _parse(root or repo_root(), _ENGINE)
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        publishes = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "record" and n.args
            and isinstance(n.args[0], ast.Name) and n.args[0].id == "rec"
            for n in ast.walk(fn))
        if not publishes:
            continue
        keys: List[str] = []
        found = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and t.id == "rec" \
                        and isinstance(node.value, ast.Dict):
                    found = True
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            keys.append(k.value)
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "rec" \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    keys.append(t.slice.value)
        if found:
            # dict-literal order, dedup preserving first occurrence
            seen: Set[str] = set()
            return tuple(k for k in keys
                         if not (k in seen or seen.add(k)))
    raise ValueError(
        f"flight tick-record builder (rec = {{...}} handed to "
        f".record(rec)) not found in {_ENGINE}")


def tests_corpus(root: Optional[Path] = None) -> str:
    root = root or repo_root()
    tdir = root / "tests"
    if not tdir.is_dir():
        return ""
    return "\n".join(p.read_text(errors="replace")
                     for p in sorted(tdir.glob("*.py")))


# ---------------------------------------------------------------------------
# the audits (injectable inputs so tests can feed synthetic surfaces)
# ---------------------------------------------------------------------------

def audit_serve_report(fields: Sequence[str], variant: Sequence[str],
                       test_corpus: str,
                       path: str = _ENGINE) -> List[Finding]:
    out: List[Finding] = []
    vset = set(variant)
    for f in fields:
        if f in vset:
            continue
        if re.search(rf"\b{re.escape(f)}\b", test_corpus):
            continue
        out.append(Finding(
            "P401", path, 0,
            f"ServeReport.{f} is neither in SHARD_VARIANT_REPORT_"
            "FIELDS nor named by any test — declare it variant "
            "(consciously widening the variant surface) or pin it in "
            "a parity/schema test"))
    fset = set(fields)
    for v in variant:
        if v not in fset:
            out.append(Finding(
                "P402", path, 0,
                f"SHARD_VARIANT_REPORT_FIELDS entry {v!r} names no "
                "ServeReport field — stale exclusion; remove it"))
    return out


def audit_flight_record(record_keys: Sequence[str],
                        planes: Sequence[str],
                        variant: Sequence[str],
                        path: str = _ENGINE) -> List[Finding]:
    out: List[Finding] = []
    allowed = set(planes) | set(variant) | set(FLIGHT_SPINE)
    for k in record_keys:
        if k not in allowed:
            out.append(Finding(
                "P403", path, 0,
                f"flight tick-record key {k!r} is neither a canonical "
                "plane (PLANES), a declared variant key "
                "(FLIGHT_VARIANT_KEYS) nor the tick spine — audit "
                "diff would never compare it"))
    kset = set(record_keys)
    for k in (*planes, *variant):
        if k not in kset:
            out.append(Finding(
                "P404", path, 0,
                f"declared flight key {k!r} is missing from the "
                "engine's tick record — every record carries every "
                "tier (the self-describing-shape contract)"))
    return out


def run_parity_audit(root: Optional[Path] = None) -> List[Finding]:
    """The repo's full parity-surface audit (what ``anomod lint`` and
    the check_contracts gate run).  A tree missing the audited sources
    (a fixture root) degrades to ONE finding naming what is missing,
    never a traceback — the gate's verdict must always be a verdict."""
    root = Path(root) if root is not None else repo_root()
    try:
        planes, fvariant = flight_contract(root)
        return (audit_serve_report(serve_report_fields(root),
                                   shard_variant_fields(root),
                                   tests_corpus(root))
                + audit_flight_record(flight_record_keys(root), planes,
                                      fvariant))
    except (OSError, ValueError, SyntaxError) as e:
        return [Finding("P401", _ENGINE, 0,
                        f"parity-surface audit could not read its "
                        f"sources under {root}: {e}")]
