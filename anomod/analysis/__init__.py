"""Contract-checking static analysis plane.

Every guarantee the serving plane ships — byte-identical N-shard vs
1-shard states, ``anomod audit replay`` reproducing a run from its
header, no-score-gap recovery — rests on conventions that used to be
enforced only by reviewer vigilance: no wall clock or unseeded RNG in
canonical-plane code, every ``ANOMOD_*`` read Config-validated, every
new ``ServeReport``/flight field either parity-pinned or on an explicit
variant list, always-copy at the ``get_state``/pool-gather seam, locks
around registry mutation.  PR 4 (scratch aliasing under async
dispatch), PR 5 (torn histogram scrapes) and PR 8 (``pool.put(None,
...)`` broadcast corruption) were all contracts violated silently and
found the hard way.  This package mechanizes those contracts as an
AST-based linter (``anomod lint`` / ``scripts/check_contracts.py``)
so the class of failure moves from runtime debugging to a CI gate.

Rule families (docs/CONTRACTS.md is the operator catalog):

- ``D1xx`` determinism: canonical-plane modules must not read the wall
  clock outside wall-leg timing form, call unseeded RNG, key on
  ``id()``, or feed set iteration into ordered output.
- ``E2xx`` env contract: every ``ANOMOD_*`` env read must be
  Config-validated or documented; dynamic (f-string/concat) reads are
  statically unresolvable and refused.
- ``S3xx`` seam discipline: pool-plane internals (``_slot`` /
  ``_slots`` / ``_runner``) stay inside the seam modules; gather-side
  returns never alias pool rows.
- ``P4xx`` parity surface: every ``ServeReport`` field and flight-tick
  key is either on the declared variant list or named by a test — a
  new field cannot silently widen the variant surface.
- ``L5xx`` lock discipline: classes owning ``self._lock`` mutate their
  shared state only inside ``with self._lock``.

Suppression syntax (reason REQUIRED — an unexplained suppression is
itself a finding)::

    x = time.time()   # anomod-lint: disable=D101 — forensic timestamp

The linter is pure stdlib ``ast`` + text: importing it never imports
jax or the serve plane, so the gate runs in milliseconds and cannot
hang on a dead device tunnel.
"""

from anomod.analysis.lint import (Finding, RULES, lint_repo, lint_source,
                                  load_baseline, repo_root, status_block)
from anomod.analysis.parity import run_parity_audit

__all__ = ["Finding", "RULES", "lint_repo", "lint_source",
           "load_baseline", "repo_root", "run_parity_audit",
           "status_block"]
