"""AST-level env-read extraction — the shared scanner behind the E2xx
lint rules and ``scripts/check_env_contract.py``'s delegation.

The PR-3 gate greps for ``ANOMOD_[A-Z0-9_]+`` tokens, which covers
every constant-key read but has a documented false negative: a
dynamically-built key (``os.environ[f"ANOMOD_{name}"]``,
``os.getenv("ANOMOD_" + name)``) contains no complete token to match.
This module walks the AST instead: it finds every read expression over
``os.environ`` / ``os.getenv`` — including aliased forms
(``from os import environ``, ``env = os.environ``) — and classifies
each key as a resolved constant name or a dynamic read with its
longest static prefix.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import List, Optional, Set


@dataclasses.dataclass(frozen=True)
class EnvRead:
    line: int
    #: fully-resolved variable name (constant or constant-foldable key)
    name: Optional[str]
    #: for dynamic keys: the leading static prefix ("" when none)
    prefix: Optional[str]


def _resolve_key(node: ast.AST) -> EnvRead:
    line = getattr(node, "lineno", 0)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return EnvRead(line, node.value, None)
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        dynamic = False
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                dynamic = True
                break
        joined = "".join(parts)
        if not dynamic:
            return EnvRead(line, joined, None)
        return EnvRead(line, None, joined)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_key(node.left)
        if left.name is not None:
            right = _resolve_key(node.right)
            if right.name is not None:
                return EnvRead(line, left.name + right.name, None)
            return EnvRead(line, None, left.name)
        return EnvRead(line, None, left.prefix or "")
    return EnvRead(line, None, "")


def _environ_aliases(nodes) -> tuple:
    """Names bound to ``os.environ`` / ``os.getenv`` in this module."""
    environ: Set[str] = set()
    getenv: Set[str] = set()
    for node in nodes:
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == "environ":
                    environ.add(a.asname or a.name)
                elif a.name == "getenv":
                    getenv.add(a.asname or a.name)
        elif isinstance(node, ast.Assign):
            src = _dotted(node.value)
            if src == "os.environ":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        environ.add(t.id)
            elif src == "os.getenv":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        getenv.add(t.id)
    return environ, getenv


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def env_reads(tree: ast.AST, ctx=None) -> List[EnvRead]:
    """Every env-var READ in ``tree`` (writes are not reads; they never
    consume a knob).  ``ctx`` (a ModuleContext) refines module-alias
    resolution (``import os as _os``) and supplies its cached node list
    (one traversal per file); without it plain ``os.`` spelling is
    assumed."""
    nodes = ctx.nodes if ctx is not None else list(ast.walk(tree))
    environ_names, getenv_names = _environ_aliases(nodes)

    def resolve(node: ast.AST) -> Optional[str]:
        if ctx is not None:
            return ctx.resolve(node)
        return _dotted(node)

    def is_environ(node: ast.AST) -> bool:
        name = resolve(node)
        if name == "os.environ":
            return True
        return isinstance(node, ast.Name) and node.id in environ_names

    out: List[EnvRead] = []
    for node in nodes:
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and is_environ(node.value):
            out.append(_resolve_key(node.slice))
        elif isinstance(node, ast.Call):
            fname = resolve(node.func)
            is_read = fname == "os.getenv" or (
                isinstance(node.func, ast.Name)
                and node.func.id in getenv_names)
            if not is_read and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("get", "setdefault") \
                    and is_environ(node.func.value):
                is_read = True
            if is_read and node.args:
                out.append(_resolve_key(node.args[0]))
    return out


def dynamic_anomod_reads(tree: ast.AST, ctx=None) -> List[EnvRead]:
    """Dynamic reads whose static prefix proves an ANOMOD_* key.
    Pass a ModuleContext to also resolve module-aliased spellings
    (``import os as _os``) — the delegating env gate does."""
    return [r for r in env_reads(tree, ctx)
            if r.name is None and r.prefix and "ANOMOD_" in r.prefix]
