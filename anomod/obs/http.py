"""Embedded /metrics endpoint plane: the framework as a scrape TARGET.

The reference's whole pipeline starts at live observability endpoints —
Prometheus ``/api/v1/query_range``, Jaeger REST — and PR 3's selfscrape
loop already proves the framework can score its OWN telemetry.  This
module closes the remaining gap: a real Prometheus (or the framework's
own live feed, anomod.serve.feed) can now scrape a running serve
process over HTTP instead of reading artifact files after the fact.

Design constraints, in order:

- **Decision planes are untouchable.**  Every handler is a pure READ of
  the process registry / flight ring — no handler mutates engine state,
  so states/alerts/SLO/shed and the canonical flight journal are
  byte-identical endpoint-on vs endpoint-off (pinned in
  tests/test_feed.py).
- **Off by default, localhost-bound.**  Serving HTTP from a benchmark
  process is opt-in (``ANOMOD_OBS_HTTP``); the bind address is always
  ``127.0.0.1`` — this is a diagnostics/dogfood plane, not an ingress.
- **Stdlib only.**  ``http.server.ThreadingHTTPServer`` on a daemon
  thread; zero new dependencies (the repo-wide constraint).

Endpoint catalog (all support GET and HEAD):

- ``/metrics`` — Prometheus text exposition via
  :func:`anomod.obs.export.to_prometheus_text`, served with the
  spec-mandated ``text/plain; version=0.0.4`` Content-Type so scrapers
  negotiate the format correctly.
- ``/healthz`` — JSON liveness: registry stats plus, when an engine is
  attached, the last-tick / virtual-clock / backlog summary.
- ``/flight`` — the attached flight recorder's bounded ring as JSON
  (404 until a recorder is attached).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from anomod.obs.export import to_prometheus_text
from anomod.obs.registry import Registry, get_registry

#: the exposition-format Content-Type the Prometheus scrape protocol
#: requires (version=0.0.4 is the text-format version, not ours)
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class ObsHttpServer:
    """Localhost-bound endpoint plane over one registry.

    ``port=0`` (the test/dogfood mode) binds an OS-assigned ephemeral
    port; read it back off :attr:`port` after :meth:`start`.  ``engine``
    and ``recorder`` are attached lazily (:meth:`attach`) because the
    serve handler builds the server before the engine exists.
    """

    def __init__(self, registry: Optional[Registry] = None,
                 port: Optional[int] = None):
        if port is None:
            from anomod.config import get_config
            port = get_config().obs_http_port
        self._registry = registry
        self._want_port = int(port)
        self._engine = None
        self._recorder = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- wiring ------------------------------------------------------------

    def attach(self, engine=None, recorder=None) -> None:
        """Attach the live engine and/or flight recorder the read-only
        handlers summarize; either may be attached later or never."""
        if engine is not None:
            self._engine = engine
            rec = getattr(engine, "flight_recorder", None)
            if recorder is None and rec is not None:
                recorder = rec
        if recorder is not None:
            self._recorder = recorder

    def registry(self) -> Registry:
        # resolved per request when constructed registry-less, so a
        # set_registry() swap (the bench's per-leg idiom) is visible
        return self._registry if self._registry is not None \
            else get_registry()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ObsHttpServer":
        if self._httpd is not None:
            return self
        plane = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence per-request stderr spam
                pass

            def _respond(self, code: int, ctype: str, body: bytes,
                         head_only: bool) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if not head_only:
                    self.wfile.write(body)

            def _serve(self, head_only: bool) -> None:
                path = self.path.split("?", 1)[0]
                try:
                    route = plane._routes().get(path)
                    if route is None:
                        self._respond(
                            404, "application/json",
                            json.dumps({"error": f"no route {path}",
                                        "routes": sorted(
                                            plane._routes())}).encode(),
                            head_only)
                        return
                    code, ctype, body = route()
                    self._respond(code, ctype, body, head_only)
                except Exception as e:  # a broken scrape must not kill
                    self._respond(     # the server thread
                        500, "application/json",
                        json.dumps({"error": f"{type(e).__name__}: "
                                             f"{e}"}).encode(),
                        head_only)

            def do_GET(self):
                self._serve(head_only=False)

            def do_HEAD(self):
                # HEAD is part of the scrape protocol (probes/uptime
                # checks issue it); same headers, no body
                self._serve(head_only=True)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._want_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="anomod-obs-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("ObsHttpServer not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def __enter__(self) -> "ObsHttpServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- handlers (pure reads) ---------------------------------------------

    def _routes(self):
        return {"/metrics": self._metrics, "/healthz": self._healthz,
                "/flight": self._flight}

    def _metrics(self):
        return 200, PROM_CONTENT_TYPE, \
            to_prometheus_text(self.registry()).encode()

    def _healthz(self):
        reg = self.registry()
        doc = {"status": "ok", "registry": {
            "enabled": reg.enabled, "n_metrics": len(reg.metrics()),
            "n_samples": reg.n_samples}}
        eng = self._engine
        if eng is not None:
            clock = getattr(eng, "clock", None)
            admission = getattr(eng, "admission", None)
            doc["engine"] = {
                "ticks": getattr(clock, "ticks", None),
                "now_s": getattr(clock, "now_s", None),
                "backlog_spans": getattr(admission, "backlog_spans", None),
            }
        return 200, "application/json", json.dumps(doc).encode()

    def _flight(self):
        rec = self._recorder
        if rec is None:
            return 404, "application/json", json.dumps(
                {"error": "no flight recorder attached"}).encode()
        doc = {"flight_format": rec.journal().get("flight_format"),
               "n_recorded": rec.n_recorded, "n_dropped": rec.n_dropped,
               "ticks": rec.records()}
        return 200, "application/json", json.dumps(doc).encode()


def maybe_serve(registry: Optional[Registry] = None
                ) -> Optional[ObsHttpServer]:
    """Start the endpoint plane iff ``ANOMOD_OBS_HTTP`` is on — the
    serve handler's one-liner.  Returns the started server or None."""
    from anomod.config import get_config
    cfg = get_config()
    if not cfg.obs_http:
        return None
    return ObsHttpServer(registry=registry, port=cfg.obs_http_port).start()
