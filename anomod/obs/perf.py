"""Performance observatory: dispatch-lifecycle timeline, overlap-bubble
accounting, and noise-aware bench regression tracking.

The third observability plane, beside the metrics registry (anomod.obs.
registry) and the flight recorder (anomod.obs.flight).  The registry
says how fast the serve plane ran, the flight recorder says what it
DECIDED — this module says where the time physically went, event by
event, and what restructuring could win back:

- **Dispatch-lifecycle timeline** (:class:`PerfRecorder`): every fused
  lane dispatch records its event timestamps — ``staged`` (scratch slot
  packed), ``submitted`` (the AOT executable call returned — the
  enqueue), ``retire`` (the coordinator started waiting on it),
  ``materialized`` (the ``block_until_ready``/host-copy execute barrier
  returned), ``folded`` (state folds applied) and ``refill`` (the
  scratch slot was next refilled) — keyed by (tick, shard, pipeline
  slot, shape).  The hooks live in the one dispatch path
  (anomod.serve.batcher.BucketRunner, the ``leg_walls()`` seam's
  module); timestamps REUSE the wall-leg ``t0``/``dt`` reads the five-leg
  decomposition already takes, so the timeline reconciles with the
  ServeReport walls to float rounding (pinned in tests/test_perf.py).
  Events ride the flight journal's VARIANT tier (the ``perf`` key in
  ``FLIGHT_VARIANT_KEYS`` — wall clock, never the parity surface) and
  export as a Chrome/Perfetto trace through the existing
  ``Tracer.to_chrome`` (:func:`perf_tracer`), one lane per
  (shard, scratch slot) with shard/slot tags in ``args``.

- **Critical-path / bubble analyzer** (:func:`analyze_events`): per
  tick, how much of the fold-leg execute WAIT is dead time that
  next-round staging could legally hide.  The model is explicit and
  deliberately an UPPER BOUND: a wait ``w_i = materialized_i -
  retire_t0_i`` (the host thread blocked on the XLA barrier) can hide
  the staging work of subsequent dispatches on the same shard whose
  scratch slot differs from ``w_i``'s (the scratch-reuse constraint:
  staging into the waited-on slot is exactly what the barrier
  protects), limited to the next ``pipeline`` such dispatches (the
  depth-legality window) with each dispatch's stage wall claimable by
  at most one wait (greedy, earliest wait first).  The sum is
  ``overlap_headroom_s`` — the go/no-go instrument for the ROADMAP
  attack "overlap the fold wait behind next-round staging": if it is a
  large fraction of the fold leg, restructuring the tick pays; if not,
  the wait is irreducible at this depth.

- **Noise-aware regression tracking** (:func:`diff_captures`): two
  bench captures compare with matched-leg pairing — DECISION metrics
  (p99/p50 latency, shed, span counts, alert counts, every parity bit)
  byte-exact, WALL metrics via bootstrap confidence intervals over
  ``raw_wall_s`` sample lists with the box noise model explicit
  (``ANOMOD_PERF_NOISE_FLOOR``, default 0.35 — this box's measured
  ±35% run-to-run floor, docs/BENCHMARKS.md).  A wall regression is
  flagged only when the whole 95% CI of the B/A mean-wall ratio sits
  above ``1 + floor`` — two same-seed captures always pass, a genuine
  2× slowdown is always named.  Scalar walls (single samples) are
  reported informationally, never flagged: one sample cannot beat the
  noise model.  ``anomod perf diff`` / ``anomod perf history`` are the
  CLI surface.

The plane is a pure read-side consumer: recording on/off leaves every
serve decision byte-identical (pinned, the PR-9 flight technique), and
the committed bench ``perf`` block prices the overhead (≤5% bar).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: perf-timeline document format (the `anomod perf record` dump)
PERF_FORMAT = 1

#: the per-dispatch event fields, in lifecycle order (the timeline
#: schema documented in docs/OBSERVABILITY.md; ``refill`` is None until
#: the slot's next reuse, which may never come for the run's last
#: dispatch per slot)
EVENT_FIELDS = ("seq", "tick", "shard", "width", "lanes", "slot",
                "staged_t0", "staged", "submitted_t0", "submitted",
                "retire_t0", "materialized", "folded", "refill",
                # the deferred-commit leg (ANOMOD_SERVE_ASYNC_COMMIT):
                # issued-at / barrier-read-at stamps, None on a
                # synchronous engine's events — appended, never
                # reordered, so PERF_FORMAT 1 readers keep working
                "deferred_t0", "deferred")


class PerfRecorder:
    """Per-shard dispatch-lifecycle event recorder.

    One recorder per shard runner (the shard-private registry
    discipline): the BucketRunner's dispatch path calls the ``note_*``
    hooks — keyed by the scratch-slot key ``(width, lanes, slot)``,
    which holds at most ONE in-flight dispatch at a time (a slot refills
    strictly after its dispatch retired, the PR-5 scratch contract), so
    the open-record map can never collide.  ``drain()`` hands the
    completed records to the coordinator at the tick barrier (the
    ``fold_verdicts`` idiom — see :func:`fold_perf_records`).

    Timestamps are ``time.perf_counter()`` seconds handed in by the
    caller — the recorder never reads a clock itself, which is what
    lets the dispatch path reuse the wall-leg reads it already takes.
    """

    def __init__(self, shard: int = 0):
        self.shard = int(shard)
        #: the engine sets this at each tick boundary (the workers are
        #: quiescent there, so no cross-thread write races a dispatch)
        self.tick = 0
        self.seq = 0
        self.n_aborted = 0
        self._open: Dict[tuple, dict] = {}
        self._last_by_key: Dict[tuple, dict] = {}
        self._done: List[dict] = []

    def note_refill(self, key: tuple, t: float) -> None:
        """The scratch slot ``key`` is being refilled at ``t`` — stamp
        the previous dispatch that used it (the slot-refilled event)."""
        last = self._last_by_key.get(key)
        if last is not None and last.get("refill") is None:
            last["refill"] = t

    def note_staged(self, key: tuple, t0: float, t1: float) -> None:
        width, lanes, slot = key
        self._open[key] = {
            "seq": self.seq, "tick": self.tick, "shard": self.shard,
            "width": int(width), "lanes": int(lanes), "slot": int(slot),
            "staged_t0": t0, "staged": t1,
            "submitted_t0": None, "submitted": None, "retire_t0": None,
            "materialized": None, "folded": None, "refill": None,
            "deferred_t0": None, "deferred": None}
        self.seq += 1

    def _rec(self, key: tuple) -> Optional[dict]:
        return self._open.get(key)

    def note_submitted(self, key: tuple, t0: float, t1: float) -> None:
        rec = self._rec(key)
        if rec is not None:
            rec["submitted_t0"] = t0
            rec["submitted"] = t1

    def note_retire(self, key: tuple, t0: float) -> None:
        rec = self._rec(key)
        if rec is not None:
            rec["retire_t0"] = t0

    def note_materialized(self, key: tuple, t: float) -> None:
        rec = self._rec(key)
        if rec is not None:
            rec["materialized"] = t

    def note_deferred(self, key: tuple, t0: float, t1: float) -> None:
        """The dispatch was left in flight under next-tick coordinator
        work from ``t0`` (issue) until the commit barrier read it at
        ``t1`` — the deferred-commit engine stamps every in-flight
        record at the barrier (once: a record re-marked by a forced
        synchronous commit keeps its first stamp)."""
        rec = self._rec(key)
        if rec is not None and rec.get("deferred_t0") is None:
            rec["deferred_t0"] = t0
            rec["deferred"] = t1

    def note_folded(self, key: tuple, t: float) -> None:
        rec = self._open.pop(key, None)
        if rec is not None:
            rec["folded"] = t
            self._last_by_key[key] = rec
            self._done.append(rec)

    def note_aborted(self, key: tuple) -> None:
        """An aborted tick discards its in-flight dispatches without
        folding (``abort_lanes``) — the open record is dropped and
        COUNTED, never silently completed as if it folded."""
        if self._open.pop(key, None) is not None:
            self.n_aborted += 1

    def drain(self) -> List[dict]:
        """Completed records since the last drain, in dispatch order
        (tick-barrier read: the runner is quiescent)."""
        done, self._done = self._done, []
        return done


def fold_perf_records(parts: Sequence[Sequence[dict]]) -> List[dict]:
    """Barrier fold of per-shard perf drains: merge on (shard, seq) so
    the folded timeline order is deterministic regardless of which
    worker drained first — the ``fold_verdicts``/``fold_leg_records``
    idiom (contents are wall clock and ride the journal's VARIANT
    tier; only the ORDER is part of the record's determinism)."""
    out = [rec for part in parts for rec in part]
    out.sort(key=lambda r: (r["shard"], r["seq"]))
    return out


# ---------------------------------------------------------------------------
# the bubble / critical-path analyzer
# ---------------------------------------------------------------------------

def _durations(ev: dict) -> Tuple[float, float, float, float, float]:
    """(stage_s, dispatch_s, wait_s, fold_s, commit_defer_s) of one
    event record — tolerant of partially-filled records (an event that
    never materialized contributes zero to the legs it never reached;
    ``commit_defer_s`` is zero on a synchronous engine's events)."""

    def span(a, b):
        if ev.get(a) is None or ev.get(b) is None:
            return 0.0
        return max(0.0, ev[b] - ev[a])

    return (span("staged_t0", "staged"),
            span("submitted_t0", "submitted"),
            span("retire_t0", "materialized"),
            span("retire_t0", "folded"),
            span("deferred_t0", "deferred"))


def analyze_events(events: Sequence[dict], pipeline: int = 1) -> dict:
    """Aggregate one batch of timeline events into leg sums and the
    overlap-headroom upper bound (model in the module docstring).

    Events are grouped by (tick, shard); within a group they are in
    dispatch order (the ``fold_perf_records`` contract).  Per group,
    each wait ``w_i`` may claim the stage walls of up to ``pipeline``
    LATER dispatches whose slot key differs from ``w_i``'s; a stage
    wall is claimable once (greedy, earliest wait first).  Returns the
    sums plus per-leg totals the reconciliation test pins against the
    five-leg ServeReport walls."""
    groups: Dict[tuple, List[dict]] = {}
    for ev in events:
        groups.setdefault((ev["tick"], ev["shard"]), []).append(ev)
    stage_s = dispatch_s = wait_s = fold_s = headroom_s = 0.0
    commit_defer_s = 0.0
    for key in sorted(groups):
        evs = groups[key]
        stages = []
        for ev in evs:
            st, dp, wt, fd, cd = _durations(ev)
            stage_s += st
            dispatch_s += dp
            wait_s += wt
            fold_s += fd
            commit_defer_s += cd
            stages.append(st)
        claimed = [False] * len(evs)
        for i, ev in enumerate(evs):
            wt = _durations(ev)[2]
            if wt <= 0.0:
                continue
            slot_key = (ev["width"], ev["lanes"], ev["slot"])
            avail = 0.0
            legal = 0
            for j in range(i + 1, len(evs)):
                if legal >= max(int(pipeline), 1):
                    break
                other = evs[j]
                if (other["width"], other["lanes"],
                        other["slot"]) == slot_key:
                    # the scratch-reuse constraint: staging into the
                    # waited-on slot IS what this barrier protects
                    break
                legal += 1
                if claimed[j]:
                    continue
                take = min(stages[j], wt - avail)
                if take > 0.0:
                    avail += take
                    if take >= stages[j]:
                        claimed[j] = True
                    else:
                        stages[j] -= take
                if avail >= wt:
                    break
            headroom_s += min(wt, avail)
    return {"n_events": len(events),
            "stage_s": stage_s, "dispatch_s": dispatch_s,
            "wait_s": wait_s, "fold_s": fold_s,
            "headroom_s": headroom_s,
            # the deferred-commit leg: time dispatches spent executing
            # under next-tick coordinator work before their barrier —
            # the HIDDEN share of the wait (0.0 on a synchronous run)
            "commit_defer_s": commit_defer_s}


def bubble_fractions(wait_s: float, headroom_s: float,
                     fold_wall_s: float, serve_wall_s: float) -> dict:
    """The per-leg bubble fractions the ServeReport carries: what share
    of the fold leg (and of the whole serve wall) is measured execute
    WAIT, and what share of each the analyzer's headroom bound says
    overlap could reclaim.  The fold leg is the only leg with an
    instrumented barrier today (stage/dispatch are host work, score is
    vectorized host math) — their bubble is 0.0 by measurement, kept in
    the dict so the schema names every leg explicitly."""
    fold = max(float(fold_wall_s), 0.0)
    serve = max(float(serve_wall_s), 0.0)

    def frac(num, den):
        return round(min(max(num, 0.0) / den, 1.0), 6) if den > 0 else 0.0

    return {"stage": 0.0, "dispatch": 0.0, "score": 0.0,
            "fold_wait_of_fold": frac(wait_s, fold),
            "fold_wait_of_serve": frac(wait_s, serve),
            "headroom_of_fold": frac(headroom_s, fold),
            "headroom_of_serve": frac(headroom_s, serve)}


def round_events(events: Sequence[dict], ndigits: int = 6) -> List[dict]:
    """Journal-compact copies (timestamps rounded to ``ndigits``) — the
    shape the flight journal's ``perf`` variant key carries."""
    out = []
    for ev in events:
        out.append({k: (round(v, ndigits) if isinstance(v, float) else v)
                    for k, v in ev.items()})
    return out


# ---------------------------------------------------------------------------
# Chrome/Perfetto export (through the existing Tracer.to_chrome)
# ---------------------------------------------------------------------------

def perf_tracer(events: Sequence[dict], service: str = "anomod-perf"):
    """A Tracer whose span list is the dispatch-lifecycle timeline —
    export with ``.to_chrome()`` / ``.dump_chrome()`` (the one chrome
    exporter, so ``spans_from_chrome`` round-trips these spans like any
    other trace).  One Perfetto lane (tid) per (shard, scratch slot);
    shard / pipeline-slot / shape tags ride each span's ``args`` so
    lanes group by shard in the UI.  Spans per dispatch:

    - ``lane.stage``     staged_t0 → staged       (host scratch pack)
    - ``lane.dispatch``  submitted_t0 → submitted (executable issue)
    - ``lane.inflight``  submitted → materialized (XLA work in flight)
    - ``lane.wait``      retire_t0 → materialized (host BLOCKED — the
      bubble the overlap analyzer prices; nested inside lane.inflight)
    - ``lane.fold``      materialized → folded    (state folds)
    - ``lane.defer``     deferred_t0 → deferred   (deferred-commit mode
      only: in flight under next-tick coordinator work — the hidden
      wait)
    """
    from anomod.utils.tracing import Tracer
    tr = Tracer(service)
    lanes: Dict[tuple, int] = {}
    for ev in sorted(events, key=lambda r: (r["shard"], r["seq"])):
        lane_key = (ev["shard"], ev["width"], ev["lanes"], ev["slot"])
        tid = lanes.setdefault(lane_key, ev["shard"] * 1000 + len(
            [k for k in lanes if k[0] == ev["shard"]]))
        tags = {"shard": ev["shard"], "slot": ev["slot"],
                "width": ev["width"], "lanes": ev["lanes"],
                "tick": ev["tick"]}
        for name, a, b in (("lane.stage", "staged_t0", "staged"),
                           ("lane.dispatch", "submitted_t0", "submitted"),
                           ("lane.inflight", "submitted", "materialized"),
                           ("lane.wait", "retire_t0", "materialized"),
                           ("lane.fold", "materialized", "folded"),
                           ("lane.defer", "deferred_t0", "deferred")):
            if ev.get(a) is None or ev.get(b) is None:
                continue
            tr.add_span(name, ev[a], max(0.0, ev[b] - ev[a]),
                        tid=tid, **tags)
    return tr


# ---------------------------------------------------------------------------
# noise-aware capture diffing (`anomod perf diff`)
# ---------------------------------------------------------------------------

#: keys whose values are seed-determined DECISIONS — byte-exact across
#: same-seed captures at any shard count / pipeline depth / residency,
#: so a mismatch is drift, not noise.  Parity sub-dicts are compared
#: wholesale (every recorded parity bit is a decision about decisions).
_DECISION_KEYS = {
    "shed_fraction", "offered_spans", "served_spans", "n_alerts",
    "fault_detection", "p99_admission_to_scored_latency_s",
    "p50_admission_to_scored_latency_s", "p99_latency_s",
    "p50_latency_s", "shed_fraction_unfused", "p99_latency_s_unfused",
    "topk_hits", "topk_hit_rate", "eligible_fault_tenants",
    "n_fault_tenants", "recorded_ticks", "dropped_ticks",
}

#: scalar wall/throughput keys reported informationally (single
#: samples — the noise model forbids flagging them)
_SCALAR_WALL_PAT = re.compile(
    r"(^|_)(spans_per_sec|wall_s|value|compile_s|overhead_fraction|"
    r"speedup)($|_)")


def _walk(doc, path=""):
    if isinstance(doc, dict):
        for k, v in doc.items():
            yield from _walk(v, f"{path}.{k}" if path else str(k))
    else:
        yield path, doc


def collect_decisions(doc: dict) -> Dict[str, object]:
    """Every decision-metric leaf of a capture, keyed by dotted path:
    the byte-exact comparison surface of :func:`diff_captures`."""
    out: Dict[str, object] = {}
    for path, val in _walk(doc):
        parts = path.split(".")
        leaf = parts[-1]
        if leaf in _DECISION_KEYS or "parity" in parts[:-1] \
                or leaf == "parity":
            out[path] = val
    return out


def collect_wall_samples(doc: dict) -> Dict[str, List[float]]:
    """Every ``raw_wall_s`` sample list, keyed by dotted path — the
    matched-leg pairing surface the bootstrap runs over."""
    out: Dict[str, List[float]] = {}
    for path, val in _walk(doc):
        if path.split(".")[-1] == "raw_wall_s" and isinstance(val, list) \
                and val and all(isinstance(x, (int, float)) for x in val):
            out[path] = [float(x) for x in val]
    return out


def collect_scalar_walls(doc: dict) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for path, val in _walk(doc):
        leaf = path.split(".")[-1]
        if isinstance(val, (int, float)) and not isinstance(val, bool) \
                and _SCALAR_WALL_PAT.search(leaf):
            out[path] = float(val)
    return out


def bootstrap_ratio_ci(a: Sequence[float], b: Sequence[float],
                       n_boot: int = 2000, seed: int = 0,
                       ) -> Tuple[float, float, float]:
    """(ratio, lo, hi): the B/A mean-wall ratio and its 95% bootstrap
    CI (seeded — two diffs of the same captures always agree)."""
    rng = np.random.default_rng(seed)
    av = np.asarray(a, np.float64)
    bv = np.asarray(b, np.float64)
    ma = av[rng.integers(0, av.size, (n_boot, av.size))].mean(axis=1)
    mb = bv[rng.integers(0, bv.size, (n_boot, bv.size))].mean(axis=1)
    ratios = mb / np.maximum(ma, 1e-12)
    lo, hi = np.quantile(ratios, [0.025, 0.975])
    return (float(bv.mean() / max(av.mean(), 1e-12)),
            float(lo), float(hi))


def default_noise_floor() -> float:
    from anomod.config import get_config
    return get_config().perf_noise_floor


def diff_captures(a: dict, b: dict,
                  noise_floor: Optional[float] = None) -> dict:
    """Compare two bench captures: decisions byte-exact, walls by
    bootstrap CI against the explicit box noise model.  Returns the
    verdict document ``anomod perf diff`` prints; ``regressions`` is
    the ordered list of statistically significant wall regressions
    (first entry = the first one, in capture order) and
    ``decision_mismatches`` the drifted decision paths."""
    floor = default_noise_floor() if noise_floor is None \
        else float(noise_floor)
    da, db = collect_decisions(a), collect_decisions(b)
    shared = sorted(set(da) & set(db))
    mismatches = [{"path": p, "a": da[p], "b": db[p]}
                  for p in shared if da[p] != db[p]]
    # a comparison that never actually compared the decision surface
    # must not report "ok": when one capture carries decision metrics
    # and the other shares NONE of them (truncated/foreign capture),
    # identical is UNKNOWN, not vacuously true.  Partial overlap stays
    # legitimate — block schemas grow across PRs, and the one-sided
    # keys are listed either way.
    coverage_gap = not shared and bool(da or db)
    wa, wb = collect_wall_samples(a), collect_wall_samples(b)
    walls = []
    regressions = []
    for path in sorted(set(wa) & set(wb)):
        ratio, lo, hi = bootstrap_ratio_ci(wa[path], wb[path])
        if lo > 1.0 + floor:
            verdict = "regression"
        elif hi < 1.0 - floor:
            verdict = "improvement"
        else:
            verdict = "within-noise"
        row = {"path": path, "ratio": round(ratio, 4),
               "ci95": [round(lo, 4), round(hi, 4)],
               "n_a": len(wa[path]), "n_b": len(wb[path]),
               "verdict": verdict}
        walls.append(row)
        if verdict == "regression":
            regressions.append(row)
    sa, sb = collect_scalar_walls(a), collect_scalar_walls(b)
    scalars = []
    for path in sorted(set(sa) & set(sb)):
        if sa[path] <= 0:
            continue
        r = sb[path] / sa[path]
        scalars.append({"path": path, "ratio": round(r, 4),
                        "outside_noise": bool(abs(r - 1.0) > floor)})
    return {
        "check": "anomod_perf_diff",
        "noise_model": {
            "floor_fraction": floor,
            "note": "walls flagged only when the whole 95% bootstrap "
                    "CI of the B/A mean ratio clears 1 + floor; "
                    "single-sample scalars are informational "
                    "(ANOMOD_PERF_NOISE_FLOOR; docs/BENCHMARKS.md "
                    "box noise model)"},
        "decisions": {"compared": len(shared),
                      "identical": (None if coverage_gap
                                    else not mismatches),
                      "only_in_a": sorted(set(da) - set(db)),
                      "only_in_b": sorted(set(db) - set(da))},
        "decision_mismatches": mismatches,
        "walls": walls,
        "scalars": scalars,
        "regressions": regressions,
        "status": ("decision-drift" if mismatches
                   else "decision-coverage-gap" if coverage_gap
                   else "wall-regression" if regressions else "ok"),
    }


# ---------------------------------------------------------------------------
# history (`anomod perf history`)
# ---------------------------------------------------------------------------

def capture_history(runs_dir) -> List[dict]:
    """Index a ``bench_runs/`` directory into a trajectory table: one
    row per capture (timestamp order), carrying the headline value and
    the decision anchors, plus the ``perf`` block's overlap headroom
    when the capture has one — "is this PR faster" read off a table
    instead of a prose hedge."""
    rows: List[dict] = []
    root = Path(runs_dir)
    for p in sorted(root.glob("*.json")):
        try:
            doc = json.loads(p.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "metric" not in doc:
            continue
        perf = doc.get("perf") if isinstance(doc.get("perf"), dict) \
            else {}
        rows.append({
            "file": p.name,
            "timestamp_utc": doc.get("timestamp_utc"),
            "git_sha": doc.get("git_sha"),
            "metric": doc.get("metric"),
            "value": doc.get("value"),
            "unit": doc.get("unit"),
            "p99_latency_s":
                doc.get("p99_admission_to_scored_latency_s"),
            "shed_fraction": doc.get("shed_fraction"),
            "n_wall_sample_legs": len(collect_wall_samples(doc)),
            "overlap_headroom_s": perf.get("overlap_headroom_s"),
        })
    rows.sort(key=lambda r: (r["timestamp_utc"] or "", r["file"]))
    return rows
