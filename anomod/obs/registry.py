"""Process-wide metrics registry: Counter / Gauge / Histogram.

The framework's whole premise is synchronized telemetry feeding anomaly
detection — so its OWN runtime emits the same three shapes every
monitoring stack does, with two twists that keep it true to the repo:

- **Histograms are t-digest sketches** (anomod.ops.tdigest — the repo's
  one sketch path), not fixed buckets: per-tenant serving telemetry is
  power-law-skewed (cf. the Sparse Allreduce observation, PAPERS.md), so
  a fixed bucket ladder either saturates or wastes resolution, while the
  digest keeps mergeable tail accuracy at a constant 32-centroid
  footprint.  The same ``TDigest`` merges the serving plane's private
  per-tenant SLO digests straight into the registry
  (:meth:`Histogram.merge_digest`).
- **The registry is a time series, not just a last-value store**:
  :meth:`Registry.scrape` appends every metric's current samples to a
  bounded journal with a caller-supplied clock (the serving plane scrapes
  on its deterministic VIRTUAL clock), and the journal exports to the
  framework's own ``MetricBatch`` / TT-CSV shapes (anomod.obs.export) so
  a run's telemetry loads back through ``load_tt_metric_csv`` and scores
  through the detector stack — the framework monitors itself.

Hot-path cost: one dict ``get`` at handle lookup (call sites cache
handles where it matters) and one small-lock update per record.  With
``ANOMOD_OBS_ENABLED=0`` every constructor returns the shared
:data:`NULL` no-op handle, so instrumented code never branches.

Metric naming convention: ``anomod_<subsystem>_<what>[_unit][_total]``
— the subsystem token is load-bearing: the self-scrape scorer
(anomod.obs.selfscrape) maps each metric to its subsystem as the
detector's "service", which is what lets an injected serve-plane stall
localize to ``serve``.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from anomod.ops.tdigest import (TDigest, tdigest_build, tdigest_merge_many,
                                tdigest_quantile)

#: digest capacity for histogram sketches (same accuracy class as the
#: serving plane's _TenantSLO digests)
_DIGEST_K = 32
#: samples buffered per histogram before folding into the digest
_FOLD_EVERY = 256


def render_labels(labels: Dict[str, str]) -> str:
    """Canonical label rendering — the io.metrics series-key shape
    (``k="v"`` sorted, comma-joined), so exported series keys read the
    same as every loaded corpus's."""
    return ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))


def subsystem_of(name: str) -> str:
    """The subsystem token of a metric name (``anomod_serve_...`` ->
    ``serve``) — the self-scrape scorer's service identity."""
    parts = name.split("_")
    if len(parts) >= 2 and parts[0] == "anomod":
        return parts[1]
    return parts[0] or "anomod"


class _NullMetric:
    """Shared no-op handle for a disabled registry: every recording
    method exists and does nothing, so instrumented hot paths never
    branch on enablement."""

    __slots__ = ()
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def merge_digest(self, digest) -> None:
        pass

    def quantile(self, q: float):
        return None

    def samples(self):
        return []


NULL = _NullMetric()


class Counter:
    """Monotone accumulator; ``samples()`` exports the running total."""

    kind = "counter"
    __slots__ = ("name", "labels", "rendered", "rev", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        #: rendered-labels cache: computed once at registration, read by
        #: every scrape/fold instead of re-sorting the label dict per
        #: metric per barrier (the dense-fold hot spot's fixed half)
        self.rendered = render_labels(self.labels)
        #: mutation generation — bumped under the metric lock on every
        #: write, so a barrier fold can skip families untouched since
        #: its last visit (Registry.delta_snapshot's dirty check)
        self.rev = 0
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += n
            self.rev += 1

    @property
    def value(self) -> float:
        return self._value

    def samples(self) -> List[Tuple[str, float]]:
        return [(self.name, self._value)]


class Gauge:
    """Last-value metric with inc/dec convenience."""

    kind = "gauge"
    __slots__ = ("name", "labels", "rendered", "rev", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self.rendered = render_labels(self.labels)
        self.rev = 0
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self.rev += 1

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            self.rev += 1

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n
            self.rev += 1

    @property
    def value(self) -> float:
        return self._value

    def samples(self) -> List[Tuple[str, float]]:
        return [(self.name, self._value)]


class Histogram:
    """t-digest-backed distribution sketch.

    ``observe`` appends to a small buffer and folds into the digest every
    ``_FOLD_EVERY`` samples (the _TenantSLO cadence) — the hot path is a
    list append, the sketch work is amortized.  ``merge_digest`` folds a
    foreign :class:`TDigest` (e.g. a serve tenant's SLO sketch) into this
    histogram's, weight-preserving, so pre-sketched telemetry joins the
    registry without replaying raw samples.
    """

    kind = "histogram"
    __slots__ = ("name", "labels", "rendered", "rev", "_lock", "_buf",
                 "_digest", "count", "sum", "_max", "_n_folds", "_q_cache")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self.rendered = render_labels(self.labels)
        self.rev = 0
        self._lock = threading.Lock()
        self._buf: List[float] = []
        self._digest: Optional[TDigest] = None
        self.count = 0
        self.sum = 0.0
        self._max = 0.0
        self._n_folds = 0
        # (fold generation, p50, p99) — the scrape path recomputes
        # quantiles only when the DIGEST changed, so a per-tick scrape
        # costs dict lookups, not a tdigest build (the <=5% serve
        # telemetry-overhead bar is won here)
        self._q_cache: Optional[Tuple[int, float, float]] = None

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._buf.append(v)
            self.count += 1
            self.sum += v
            self._max = max(self._max, v)
            self.rev += 1
            if len(self._buf) >= _FOLD_EVERY:
                self._fold_locked()

    def merge_digest(self, digest: TDigest) -> None:
        """Fold a pre-built digest in (count/sum book via its weights)."""
        w = float(np.asarray(digest.weight).sum())
        if w <= 0:
            return
        with self._lock:
            self.count += int(round(w))
            self.sum += float((np.asarray(digest.mean)
                               * np.asarray(digest.weight)).sum())
            self._max = max(self._max,
                            float(np.asarray(digest.mean)[
                                np.asarray(digest.weight) > 0].max()))
            self._digest = digest if self._digest is None else \
                tdigest_merge_many([self._digest, digest])
            self._n_folds += 1
            self.rev += 1

    def _fold_locked(self) -> None:
        if not self._buf:
            return
        d = tdigest_build(np.asarray(self._buf, np.float32), k=_DIGEST_K)
        self._digest = d if self._digest is None else \
            tdigest_merge_many([self._digest, d])
        self._buf = []
        self._n_folds += 1

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            self._fold_locked()
            if self._digest is None or \
                    float(self._digest.weight.sum()) <= 0:
                return None
            return float(tdigest_quantile(self._digest, q))

    def _quantiles_cached_locked(self) -> Optional[Tuple[float, float]]:
        """(p50, p99) from the digest alone, recomputed only when the
        digest changed.  The scrape path's cheap read: pending buffer
        samples fold in early only once enough of them pile up (64), so
        scrape-time quantiles may lag the newest few observations — the
        price of a per-tick scrape that costs microseconds.  Caller
        holds ``self._lock``."""
        if self._digest is None or len(self._buf) >= 64:
            self._fold_locked()
        if self._digest is None:
            return None
        cached = self._q_cache
        if cached is not None and cached[0] == self._n_folds:
            return cached[1], cached[2]
        if float(self._digest.weight.sum()) <= 0:
            return None
        p50 = float(tdigest_quantile(self._digest, 0.5))
        p99 = float(tdigest_quantile(self._digest, 0.99))
        self._q_cache = (self._n_folds, p50, p99)
        return p50, p99

    def drain_digest(self) -> Optional[TDigest]:
        """Fold pending samples, hand the digest out, and RESET this
        histogram — the move-semantics half of :meth:`merge_digest`, so
        a worker registry's histogram can fold into the process
        registry repeatedly without double counting (Registry.fold_from
        at ``final=True``).  Returns None when nothing was observed."""
        with self._lock:
            self._fold_locked()
            digest, self._digest = self._digest, None
            self.count = 0
            self.sum = 0.0
            self._max = 0.0
            self._n_folds += 1
            self._q_cache = None
            self.rev += 1
            return digest

    def samples(self) -> List[Tuple[str, float]]:
        # ONE locked snapshot: count, sum, max and the quantiles must
        # come from the same instant.  Reading count/sum outside the
        # lock (the pre-shard behavior) let a scrape race a concurrent
        # observe() between the two attribute reads and journal a count
        # that disagrees with its sum — harmless for one process-wide
        # tick loop, visibly torn once shard worker threads record while
        # the coordinator scrapes (tests/test_obs.py hammer-pins this).
        with self._lock:
            out = [(f"{self.name}_count", float(self.count)),
                   (f"{self.name}_sum", self.sum)]
            qs = self._quantiles_cached_locked()
            if qs is not None:
                out.append((f"{self.name}_p50", qs[0]))
                out.append((f"{self.name}_p99", qs[1]))
                out.append((f"{self.name}_max", self._max))
            return out


#: one journal row: (t_s, sample_name, series_labels_rendered, value)
Sample = Tuple[float, str, str, float]


class Registry:
    """Thread-safe metric registry + bounded scrape journal.

    ``enabled``/``max_samples`` default from the validated Config env
    contract (``ANOMOD_OBS_ENABLED`` / ``ANOMOD_OBS_MAX_SAMPLES``).
    """

    def __init__(self, enabled: Optional[bool] = None,
                 max_samples: Optional[int] = None):
        if enabled is None or max_samples is None:
            from anomod.config import get_config
            cfg = get_config()
            enabled = cfg.obs_enabled if enabled is None else enabled
            max_samples = (cfg.obs_max_samples if max_samples is None
                           else max_samples)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, str], object] = {}
        self._journal: "collections.deque[Sample]" = collections.deque(
            maxlen=int(max_samples))

    # -- handle construction (memoized by name + rendered labels) ---------

    def _get(self, cls, name: str, labels: Dict[str, str]):
        if not self.enabled:
            return NULL
        key = (name, render_labels(labels))
        got = self._metrics.get(key)
        if got is None:
            with self._lock:
                got = self._metrics.get(key)
                if got is None:
                    got = cls(name, labels)
                    self._metrics[key] = got
        if not isinstance(got, cls):
            raise ValueError(
                f"metric {name!r} already registered as {got.kind}, "
                f"not {cls.kind}")
        return got

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def metrics(self) -> List[object]:
        with self._lock:
            return list(self._metrics.values())

    # -- time series -------------------------------------------------------

    def scrape(self, now_s: Optional[float] = None) -> int:
        """Append every metric's current samples to the journal.

        ``now_s`` is the caller's clock — wall time by default, the
        VIRTUAL clock for the serving plane, so a seeded serve run's
        self-scrape timeline is deterministic and windows bin cleanly.
        Returns the number of samples appended (0 when disabled)."""
        if not self.enabled:
            return 0
        if now_s is None:
            import time
            now_s = time.time()
        rows = []
        for m in self.metrics():
            series = m.rendered
            for sname, val in m.samples():
                rows.append((float(now_s), sname, series, float(val)))
        # journal mutation belongs under the registry lock (the L501
        # lock-discipline contract): appending row-by-row unlocked let
        # a concurrent scrape interleave its rows into this one's block
        # — same torn-read family as the pre-PR-5 Histogram.samples.
        # Rows are built FIRST (each m.samples() takes its own metric
        # lock; never nested with ours) so the critical section is one
        # extend.
        with self._lock:
            self._journal.extend(rows)
        return len(rows)

    @property
    def n_samples(self) -> int:
        return len(self._journal)

    def journal(self) -> List[Sample]:
        return list(self._journal)

    def snapshot(self) -> dict:
        """Point-in-time JSON-able view of every metric (no journal)."""
        out: Dict[str, dict] = {}
        for m in self.metrics():
            key = m.name if not m.labels else \
                f"{m.name}{{{render_labels(m.labels)}}}"
            if m.kind == "histogram":
                out[key] = {"kind": m.kind, "count": m.count,
                            "sum": round(m.sum, 6)}
                p50 = m.quantile(0.5)
                if p50 is not None:
                    out[key].update(p50=round(p50, 6),
                                    p99=round(m.quantile(0.99), 6))
            else:
                out[key] = {"kind": m.kind, "value": m.value}
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._journal.clear()

    # -- worker-registry fold (the sharded serving plane's seam) -----------
    #
    # The barrier merge is split into a picklable DELTA snapshot
    # (delta_snapshot, taken where the metrics live — a worker thread's
    # registry in-process, a worker PROCESS's registry across a pipe)
    # and a coordinator-side APPLY (apply_delta).  fold_from composes
    # the two, so the thread engine's fold and the process engine's
    # barrier payload are ONE code path and can never drift.

    def delta_snapshot(self, state: Dict[tuple, float],
                       mode: str = "sparse", final: bool = False) -> dict:
        """Serialize this registry's change since ``state`` as a
        picklable delta — the tick barrier's wire shape.

        ``sparse`` visits every family but SKIPS the ones whose ``rev``
        generation matches the high-water in ``state`` (untouched since
        the previous snapshot): the dirty check is two dict probes, so
        barrier cost follows touched families — O(active tenants'
        metrics) under Zipf traffic, not registered fleet size.
        ``dense`` serializes every family every time (the payload
        oracle the sparse win is measured against): all counters (zero
        deltas included), all gauges, and every histogram's full
        current digest snapshot.  Applying either produces the same
        registry bytes — dense just ships more to say it.

        Histogram entries carry ``(mean, weight)`` centroid arrays.  At
        ``final=True`` they are DRAINED from the source (move
        semantics, exactly :meth:`Histogram.drain_digest`) and meant to
        merge; dense non-final entries are non-draining snapshots that
        :meth:`apply_delta` deliberately ignores.

        ``state`` is owned by the caller (one dict per source registry)
        and carries both the counter high-waters — keyed ``(name,
        rendered_labels)``, the historic fold_from shape — and the rev
        marks, keyed ``("rev", name, rendered_labels)``.
        """
        if mode not in ("sparse", "dense"):
            raise ValueError(f"unknown fold mode {mode!r} (dense|sparse)")
        sparse = mode == "sparse"
        counters: list = []
        gauges: list = []
        hists: list = []
        for m in self.metrics():
            rkey = ("rev", m.name, m.rendered)
            if m.kind == "counter":
                if sparse and state.get(rkey) == m.rev:
                    continue
                state[rkey] = m.rev
                key = (m.name, m.rendered)
                prev = state.get(key, 0.0)
                cur = m.value
                if cur > prev:
                    state[key] = cur
                    counters.append((m.name, tuple(sorted(m.labels.items())),
                                     cur - prev))
                elif not sparse:
                    counters.append((m.name, tuple(sorted(m.labels.items())),
                                     0.0))
            elif m.kind == "gauge":
                if sparse and state.get(rkey) == m.rev:
                    continue
                state[rkey] = m.rev
                gauges.append((m.name, tuple(sorted(m.labels.items())),
                               m.value))
            elif m.kind == "histogram":
                if final:
                    digest = m.drain_digest()
                    if digest is not None:
                        hists.append((m.name,
                                      tuple(sorted(m.labels.items())),
                                      np.asarray(digest.mean, np.float32),
                                      np.asarray(digest.weight,
                                                 np.float32)))
                elif not sparse:
                    with m._lock:
                        m._fold_locked()
                        digest = m._digest
                        if digest is not None:
                            hists.append((
                                m.name, tuple(sorted(m.labels.items())),
                                np.asarray(digest.mean, np.float32).copy(),
                                np.asarray(digest.weight,
                                           np.float32).copy()))
        return {"mode": mode, "final": bool(final), "counters": counters,
                "gauges": gauges, "hists": hists}

    def apply_delta(self, delta: Optional[dict],
                    shard: Optional[str] = None) -> None:
        """Fold one :meth:`delta_snapshot` into this registry — the
        coordinator half of the barrier merge.  Counter entries
        increment (zero deltas skipped), gauge entries set a
        ``shard``-labeled twin when ``shard`` is given (a gauge is a
        per-shard fact), histogram entries merge their centroid sets
        through :meth:`Histogram.merge_digest` ONLY on a final delta
        (non-final dense snapshots are informational payload, not
        mergeable state)."""
        if delta is None or not self.enabled:
            return
        for name, litems, d in delta["counters"]:
            if d > 0:
                self.counter(name, **dict(litems)).inc(d)
        for name, litems, v in delta["gauges"]:
            labels = dict(litems)
            if shard is not None:
                labels["shard"] = shard
            self.gauge(name, **labels).set(v)
        if delta["final"]:
            from anomod.ops.tdigest import TDigest
            for name, litems, mean, weight in delta["hists"]:
                self.histogram(name, **dict(litems)).merge_digest(
                    TDigest(mean=np.asarray(mean, np.float32),
                            weight=np.asarray(weight, np.float32)))

    def fold_from(self, src: "Registry", state: Dict[tuple, float],
                  shard: Optional[str] = None, final: bool = False,
                  mode: str = "sparse") -> Optional[dict]:
        """Fold a worker registry into this one at the tick barrier.

        Each serve shard records its runner's hot-path metrics into its
        OWN registry (zero cross-thread contention per dispatch); the
        coordinator folds the shards in at the barrier:

        - **Counters** increment by the delta since the previous fold
          (``state`` carries the per-metric high-water marks), so the
          process-registry counter stays the summable fleet total.
        - **Gauges** set a ``shard``-labeled twin (a gauge is a
          per-shard fact — pad-waste on shard 2 is not a fleet sum).
        - **Histograms** DRAIN at ``final=True`` (run end): the source
          digest folds through :meth:`Histogram.merge_digest` — exactly
          how the per-tenant SLO digests already join the registry —
          and is then cleared on the source, so repeated final folds
          (an engine run() twice) neither double-count nor drop data.

        ``mode`` selects the snapshot discipline (the validated
        ANOMOD_SERVE_FOLD value): ``sparse`` (default) skips families
        untouched since the previous fold via the per-metric ``rev``
        dirty marks — scrape output is pinned byte-identical to a dense
        walk, the walk is just cheaper.  Returns the applied delta so
        barrier callers can account payload bytes (None when either
        side is disabled).

        The caller owns the quiescence contract: fold at a barrier,
        with the worker that records into ``src`` idle.
        """
        if not (self.enabled and src.enabled):
            return None
        delta = src.delta_snapshot(state, mode=mode, final=final)
        self.apply_delta(delta, shard=shard)
        return delta


def delta_nbytes(delta: Optional[dict]) -> int:
    """Structural payload size of one :meth:`Registry.delta_snapshot`
    in bytes — key strings at utf-8 length, 8 bytes per float scalar,
    8 bytes per digest centroid component.  A deterministic accounting
    (identical on every box and in both worker modes), NOT a pickle
    length: the sparse-vs-dense win criterion needs exact,
    box-independent byte counts."""
    if delta is None:
        return 0
    n = 0
    for name, litems, _ in delta["counters"]:
        n += len(name.encode()) + 8
        n += sum(len(k.encode()) + len(str(v).encode()) for k, v in litems)
    for name, litems, _ in delta["gauges"]:
        n += len(name.encode()) + 8
        n += sum(len(k.encode()) + len(str(v).encode()) for k, v in litems)
    for name, litems, mean, weight in delta["hists"]:
        n += len(name.encode()) + 8 * (len(mean) + len(weight))
        n += sum(len(k.encode()) + len(str(v).encode()) for k, v in litems)
    return n


_DEFAULT: Optional[Registry] = None
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> Registry:
    """The process-wide registry (constructed lazily from the env
    contract so import order never races config)."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = Registry()
    return _DEFAULT


def set_registry(registry: Registry) -> Registry:
    """Swap the process-wide registry (tests, the bench's off/on pair);
    returns the previous one so callers can restore it."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev, _DEFAULT = _DEFAULT, registry
    return prev if prev is not None else registry
