"""Black-box flight recorder: the serve plane's tick-level journal,
deterministic audit replay, and divergence bisection.

The paper's premise is synchronized telemetry that makes faults
diagnosable *after the fact* (SURVEY.md §0); metrics and traces (PR 3)
say how the serve plane *performed*, but nothing records what the engine
*decided* each tick.  This module is that record: an always-on,
bounded-overhead ring journal of every serve tick — admission decisions,
the dispatch plan, the five-leg wall decomposition, alerts, RCA verdicts
and a cheap periodic tenant-state digest — self-describing (seed,
resolved Config snapshot, versions in the header) and atomically
dumpable.  ``anomod audit`` turns it into a forensic tool: ``record``
runs traffic with the recorder on, ``replay`` re-executes from the
header's seed+config (optionally at a different shard count / pipeline
depth / state residency — the determinism contracts under test), and
``diff`` compares two journals tick-aligned and names the FIRST
divergent tick and which PLANE diverged.

Two tiers of content per tick record, mirroring the serving plane's
``SHARD_VARIANT_REPORT_FIELDS`` discipline:

- the **canonical planes** (:data:`PLANES` — admission, dispatch, fold,
  score, rca) hold only seed-determined decisions: admission counts and
  a crc32 digest of the served decision set, staged-chunk counts per
  width (identical under every execution strategy — the batcher's
  ``stage_plan`` is the one staging definition), the cadenced
  tenant-state digest (crc32 over the ``get_state``/pool-gather bytes —
  pinned byte-exact across residencies), the running alert-stream
  digest, and the running RCA-verdict digest.  Same seed ⇒ byte-identical
  canonical journals across reruns, shard counts, pipeline depths and
  host-vs-device state (tests/test_flight.py pins all four).
- the **variant keys** (:data:`FLIGHT_VARIANT_KEYS` — ``walls``,
  ``topology``) hold wall-clock measurements (the five-leg
  stage/dispatch/fold/score/other decomposition per tick) and lane/shard
  grouping topology (which lanes shared a fused stack, per-shard leg
  walls folded at the tick barrier in shard order — the
  ``fold_verdicts`` idiom).  They ride in the dump for forensics and are
  EXCLUDED from the canonical byte surface and from ``diff``.

Durability follows the repo's one publish idiom (tmp + ``os.replace``):
a killed run never leaves a truncated journal behind a valid path.  The
ring is bounded (``ANOMOD_FLIGHT_MAX_TICKS``) and every eviction is
counted (``anomod_flight_dropped_ticks_total`` + the per-recorder
``n_dropped``) — loss is visible, never silent.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import zlib
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from anomod import obs

#: journal format version (bumped on any canonical-shape change: a diff
#: across formats would bisect shape drift, not behavior)
FLIGHT_FORMAT = 1

#: the canonical decision planes, in CAUSAL order — when several planes
#: diverge in the same tick, ``diff_journals`` names the earliest: a
#: wrong admission decision makes every downstream plane diverge too,
#: and the culprit is the first wrong decision, not its echoes.
PLANES: Tuple[str, ...] = ("admission", "dispatch", "fold", "score", "rca")

#: per-tick keys excluded from the canonical byte surface and from
#: ``diff``: wall-clock measurements, shard/lane grouping topology, the
#: supervisor's recovery events (what crashed/respawned/migrated is
#: execution-strategy forensics — the no-score-gap contract pins the
#: DECISION planes of a recovered run equal to fault-free, so recovery
#: marks must never touch them), and the elastic policy's scaling
#: events (what scaled up/down/rebalanced is likewise execution
#: topology: an elastic run's canonical planes stay equal to a static
#: run's), and the performance observatory's per-tick dispatch-
#: lifecycle timeline (anomod.obs.perf — pure wall-clock event
#: timestamps plus the overlap-headroom bound computed from them), and
#: the fleet census observatory's resident-bytes/hot-set records
#: (anomod.obs.census — deterministic and wall-free, but per-shard
#: pool/scratch bytes follow the execution TOPOLOGY, so the key is
#: variant like ``topology``; unlike ``walls``/``perf`` the census
#: stream is byte-equal across same-seed reruns of one topology,
#: pinned in tests/test_census.py) —
#: the flight twin of the serving plane's
#: SHARD_VARIANT_REPORT_FIELDS (one definition, shared by
#: canonical_ticks, the parity tests and the pre-bench flight smoke).
#: ``tiering`` (anomod.serve.tiering) joins the variant tier for one
#: precise reason: demote/promote/miss events are wall-free functions
#: of seed+config (byte-equal across same-config reruns, pinned in
#: tests/test_serve_tiering.py), but a cold promotion's one-tick
#: deferral legitimately moves WHICH tick a tenant's fold/score deltas
#: land in vs a never-evicted run of the same seed — content conserved,
#: placement shifted — so the key cannot sit on the canonical surface.
FLIGHT_VARIANT_KEYS: Tuple[str, ...] = ("walls", "topology", "recovery",
                                        "scaling", "perf", "census",
                                        "tiering")


def crc_text(text: str, prev: int = 0) -> int:
    """Running crc32 over a text chunk (stable across processes and
    Python hash seeds — the shard-partition idiom)."""
    return zlib.crc32(text.encode(), prev) & 0xFFFFFFFF


def crc_bytes(data: bytes, prev: int = 0) -> int:
    return zlib.crc32(data, prev) & 0xFFFFFFFF


def state_digest(replays: Dict[int, object], prev: int = 0) -> int:
    """crc32 over every tenant replay state, in sorted-tenant order.

    Reads through the ``get_state`` seam (a pool-backed replay gathers
    its slot; the host seam hands its pytree) — pinned byte-exact across
    residencies, which is what makes one digest comparable between a
    host-seam and a device-pool run.  The ring anchor
    (``window_offset``) and span count prefix each tenant so two states
    that happen to share bytes at different anchors still differ."""
    crc = prev
    for tid in sorted(replays):
        rep = replays[tid]
        st = rep.get_state() if hasattr(rep, "get_state") else rep.state
        crc = crc_text(f"{tid}:{getattr(rep, 'window_offset', 0)}"
                       f":{getattr(rep, 'n_spans', 0)}:", crc)
        crc = crc_bytes(np.ascontiguousarray(st.agg).tobytes(), crc)
        crc = crc_bytes(np.ascontiguousarray(st.hist).tobytes(), crc)
    return crc


def _gf2_matrix_times(mat: List[int], vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _gf2_matrix_square(mat: List[int]) -> List[int]:
    return [_gf2_matrix_times(mat, mat[n]) for n in range(32)]


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """zlib's crc32_combine in pure Python: the crc of ``A + B`` from
    ``crc32(A)``, ``crc32(B)`` and ``len(B)`` alone (GF(2) matrix
    shift).  This is what lets a worker PROCESS hand the coordinator
    per-tenant digest fragments — ``(crc, length)`` pairs, a few bytes
    each — instead of shipping whole state pytrees across the pipe,
    while the folded digest stays bit-equal to :func:`state_digest`'s
    sequential walk (pinned in tests/test_serve_procshard.py)."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    odd = [0xEDB88320]          # CRC-32 polynomial, reflected
    row = 1
    for _ in range(31):
        odd.append(row)
        row <<= 1
    even = _gf2_matrix_square(odd)
    odd = _gf2_matrix_square(even)
    while True:
        even = _gf2_matrix_square(odd)
        if len2 & 1:
            crc1 = _gf2_matrix_times(even, crc1)
        len2 >>= 1
        if len2 == 0:
            break
        odd = _gf2_matrix_square(even)
        if len2 & 1:
            crc1 = _gf2_matrix_times(odd, crc1)
        len2 >>= 1
        if len2 == 0:
            break
    return (crc1 ^ crc2) & 0xFFFFFFFF


def state_digest_parts(replays: Dict[int, object]) -> List[Tuple[int, int,
                                                                 int]]:
    """The worker-side half of :func:`state_digest`: per-tenant
    ``(tenant_id, chunk_crc, chunk_len)`` fragments over exactly the
    bytes the sequential walk would consume (prefix + agg + hist).
    Each fragment is computed where the state lives; the coordinator
    folds fragments from every shard in global sorted-tenant order with
    :func:`fold_digest_parts`."""
    parts = []
    for tid in sorted(replays):
        rep = replays[tid]
        st = rep.get_state() if hasattr(rep, "get_state") else rep.state
        chunk = (f"{tid}:{getattr(rep, 'window_offset', 0)}"
                 f":{getattr(rep, 'n_spans', 0)}:".encode()
                 + np.ascontiguousarray(st.agg).tobytes()
                 + np.ascontiguousarray(st.hist).tobytes())
        parts.append((int(tid), crc_bytes(chunk), len(chunk)))
    return parts


def fold_digest_parts(parts: List[Tuple[int, int, int]],
                      prev: int = 0) -> int:
    """Coordinator fold of :func:`state_digest_parts` fragments (from
    any number of shards) into the running digest — bit-equal to
    :func:`state_digest` over the union of the shards' replays."""
    crc = prev
    for _tid, chunk_crc, chunk_len in sorted(parts):
        crc = crc32_combine(crc, chunk_crc, chunk_len)
    return crc


def config_snapshot() -> dict:
    """The resolved Config as a JSON-able dict (Paths stringified) —
    the header's "what knobs was this run serving under" record."""
    from anomod.config import get_config
    cfg = get_config()
    out = {}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, Path):
            v = str(v)
        elif isinstance(v, tuple):
            v = [list(x) if isinstance(x, tuple) else x for x in v]
        out[f.name] = v
    return out


def versions() -> dict:
    import platform as _platform

    import jax
    out = {"python": _platform.python_version(), "jax": jax.__version__,
           "numpy": np.__version__}
    try:
        import jaxlib
        out["jaxlib"] = jaxlib.__version__
    except Exception:
        pass
    return out


def canonical_ticks(ticks: List[dict]) -> List[dict]:
    """The byte-parity view of a tick list: every record with the
    variant keys (:data:`FLIGHT_VARIANT_KEYS`) stripped."""
    return [{k: v for k, v in rec.items()
             if k not in FLIGHT_VARIANT_KEYS} for rec in ticks]


def _atomic_write_json(path, doc: dict) -> Path:
    """The one publish idiom (tmp + ``os.replace``, anomod.io.cache) for
    this module's two documents — a killed run never leaves a truncated
    journal or bundle behind a valid path."""
    path = Path(path)
    if path.parent and not path.parent.exists():
        path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    try:
        tmp.write_text(json.dumps(doc, sort_keys=True))
        os.replace(tmp, path)
    finally:
        if tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass
    return path


class FlightRecorder:
    """Bounded ring journal of serve-tick records.

    The ENGINE builds each record (it owns the decision state); the
    recorder owns bounding, counting, the canonical surface and
    publication.  ``header`` is the self-describing preamble — engine
    shape, resolved Config snapshot, versions, and (when driven through
    ``run_power_law``) the ``run`` kwargs ``anomod audit replay``
    re-executes from."""

    def __init__(self, header: dict, max_ticks: Optional[int] = None,
                 digest_every: Optional[int] = None):
        from anomod.config import get_config
        cfg = get_config()
        self.max_ticks = int(cfg.flight_max_ticks if max_ticks is None
                             else max_ticks)
        self.digest_every = int(cfg.flight_digest_every
                                if digest_every is None else digest_every)
        if self.max_ticks < 1:
            raise ValueError("flight ring needs >= 1 tick")
        if self.digest_every < 1:
            raise ValueError("digest cadence must be >= 1 tick")
        self.header = dict(header)
        self.header.setdefault("flight_format", FLIGHT_FORMAT)
        self.header["digest_every"] = self.digest_every
        self.header["max_ticks"] = self.max_ticks
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self.max_ticks)
        self.n_recorded = 0
        self.n_dropped = 0
        self.dump_error: Optional[str] = None
        # registry mirrors: recording is per-tick on the serve hot path,
        # handles cached; the drop counter is the no-silent-loss pin
        self._obs_ticks = obs.counter("anomod_flight_ticks_total")
        self._obs_dropped = obs.counter(
            "anomod_flight_dropped_ticks_total")
        self._obs_dumps = obs.counter("anomod_flight_dumps_total")
        self._obs_dump_errors = obs.counter(
            "anomod_flight_dump_errors_total")

    def digest_tick(self, tick_idx: int) -> bool:
        """Whether ``tick_idx`` (0-based) is a state-digest tick — the
        cadence contract shared with the engine and documented for
        ``diff`` (journals only compare digests at matching cadence)."""
        return (tick_idx + 1) % self.digest_every == 0

    def record(self, rec: dict) -> None:
        if len(self._ring) == self.max_ticks:
            self.n_dropped += 1
            self._obs_dropped.inc()
        self._ring.append(rec)
        self.n_recorded += 1
        self._obs_ticks.inc()

    def records(self) -> List[dict]:
        return list(self._ring)

    def canonical_bytes(self) -> bytes:
        """The journal's byte-parity surface: the canonical tick records
        (variant keys stripped), serialized deterministically.  Same
        seed ⇒ equal bytes across reruns, shard counts, pipeline depths
        and state residencies."""
        return json.dumps({"flight_format": FLIGHT_FORMAT,
                           "ticks": canonical_ticks(self.records())},
                          sort_keys=True,
                          separators=(",", ":")).encode()

    def journal(self) -> dict:
        """The full journal document (header + counters + every record,
        variant keys included) — what :meth:`dump` publishes and
        :func:`diff_journals` consumes."""
        return {"flight_format": FLIGHT_FORMAT, "header": dict(self.header),
                "n_recorded": self.n_recorded, "n_dropped": self.n_dropped,
                "ticks": self.records()}

    def dump(self, path) -> dict:
        """Atomic publish of :meth:`journal`; returns the dict it
        wrote."""
        doc = self.journal()
        _atomic_write_json(path, doc)
        return doc

    def forensic(self, path, registry=None, tracer=None,
                 reason: str = "") -> Optional[str]:
        """Alert/SLO-breach forensic dump: ring snapshot + registry
        scrape + tracer spans in ONE atomically-published bundle.

        An OSError (disk full, unwritable dir) must not kill the serve
        tick that triggered the dump — it is counted
        (``anomod_flight_dump_errors_total``), recorded on
        ``dump_error``, and the tick proceeds; any other failure is a
        bug and propagates."""
        try:
            out = forensic_bundle(path, self, registry=registry,
                                  tracer=tracer, reason=reason)
            self._obs_dumps.inc()
            return str(out)
        except OSError as e:
            self.dump_error = f"{type(e).__name__}: {e}"
            self._obs_dump_errors.inc()
            return None


def forensic_bundle(path, recorder: FlightRecorder, registry=None,
                    tracer=None, reason: str = "") -> Path:
    """One forensic document: the flight journal, the metric registry's
    point-in-time snapshot + scrape journal, and the tracer's Jaeger
    spans — atomically published, so the bundle behind a valid path is
    always complete."""
    doc = {"bundle": "anomod-flight-forensic", "reason": str(reason),
           "flight": recorder.journal()}
    if registry is not None and getattr(registry, "enabled", False):
        doc["registry"] = {"snapshot": registry.snapshot(),
                           "journal": [list(s) for s
                                       in registry.journal()]}
    if tracer is not None:
        doc["trace"] = tracer.to_jaeger()
    return _atomic_write_json(path, doc)


def load_journal(path) -> dict:
    """Load a dumped journal; fails loud on a non-flight document (a
    diff against some other JSON would report nonsense ticks)."""
    doc = json.loads(Path(path).read_text())
    if not isinstance(doc, dict) or "ticks" not in doc \
            or doc.get("flight_format") != FLIGHT_FORMAT:
        raise ValueError(f"not a flight journal (format "
                         f"{FLIGHT_FORMAT}): {path}")
    return doc


def diff_journals(a: dict, b: dict) -> Optional[dict]:
    """Tick-aligned comparison of two journals' canonical planes.

    Returns ``None`` when the canonical surfaces are identical,
    otherwise a dict naming the FIRST divergent tick and the earliest
    divergent PLANE in causal order (:data:`PLANES`; ``clock`` = the
    tick index/virtual-time spine itself, ``length`` = one journal ran
    more ticks) with both sides' plane records — the bisection verdict
    ``anomod audit diff`` prints and exits nonzero on.  Wall-clock and
    topology keys never participate (:data:`FLIGHT_VARIANT_KEYS`).
    """
    ta = canonical_ticks(a.get("ticks", ()))
    tb = canonical_ticks(b.get("ticks", ()))
    notes: List[str] = []
    ha, hb = a.get("header", {}), b.get("header", {})
    if ha.get("digest_every") != hb.get("digest_every"):
        notes.append(
            f"digest cadence differs (a={ha.get('digest_every')}, "
            f"b={hb.get('digest_every')}): fold digests land on "
            "different ticks and will read as fold divergence")
    if a.get("n_dropped") or b.get("n_dropped"):
        notes.append(f"ring drops (a={a.get('n_dropped', 0)}, "
                     f"b={b.get('n_dropped', 0)}): journals may start "
                     "at different ticks")

    def verdict(i, plane, va, vb):
        out = {"tick": (ta[i].get("tick", i) if i < len(ta)
                        else tb[i].get("tick", i)),
               "index": i, "plane": plane, "a": va, "b": vb}
        if notes:
            out["notes"] = notes
        return out

    for i in range(min(len(ta), len(tb))):
        ra, rb = ta[i], tb[i]
        spine_a = (ra.get("tick"), ra.get("now_s"), ra.get("final"))
        spine_b = (rb.get("tick"), rb.get("now_s"), rb.get("final"))
        if spine_a != spine_b:
            return verdict(i, "clock", list(spine_a), list(spine_b))
        for plane in PLANES:
            if ra.get(plane) != rb.get(plane):
                return verdict(i, plane, ra.get(plane), rb.get(plane))
    if len(ta) != len(tb):
        i = min(len(ta), len(tb))
        return verdict(i, "length", len(ta), len(tb))
    return None
