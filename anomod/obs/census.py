"""Fleet census observatory: deterministic resident-bytes accounting,
hot-set/Zipf telemetry, and O(registered)-vs-O(active) tick-cost
attribution.

The fourth observability plane, beside the metrics registry
(anomod.obs.registry), the flight recorder (anomod.obs.flight) and the
performance observatory (anomod.obs.perf).  The registry says how fast
the serve plane ran, the flight recorder what it DECIDED, the perf
observatory where the time went — this module says what the plane
HOLDS, per tenant and per byte, and which of its costs scale with the
REGISTERED fleet rather than the ACTIVE one.  It is the instrument the
ROADMAP's million-tenant tiering item ("O(hot-set) ticks",
resident-bytes and demotion/promotion counters) lands against: the
tiering refactor must flatten the baseline curves this module commits.

Three instruments, all pure READ-side consumers (census on/off leaves
every serve decision — states, alerts, SLO, shed, the canonical flight
journal — byte-identical; pinned in tests/test_census.py):

- **Resident-bytes accounting** (:func:`collect_resident_bytes`):
  per-(shard, plane) byte counts computed DETERMINISTICALLY from array
  shapes/dtypes and container lengths — never a psutil/RSS wall, so the
  same seed produces the same bytes on every rerun, at any wall speed.
  Planes: the :class:`anomod.replay.TenantStatePool` device slots (or
  the host-seam per-tenant states — same per-slot shape either way)
  and the runner's pinned lane scratch (anomod.serve.batcher), the
  admission registries/queues (anomod.serve.queues — queued span
  arrays exact, per-registered-tenant bookkeeping at documented
  nominal entry sizes), the per-tenant SLO t-digests, the online-RCA
  evidence buffers (anomod.serve.rca), and the flight/perf recorder
  retentions (container length × schema-derived record size).  The
  pool total is PINNED to reconcile exactly with
  ``(capacity + 1) × per-slot nbytes`` (row 0 is the dead slot) — a
  census whose pool arithmetic drifts from the arrays it describes is
  lying, and the ``pool_reconciled`` bit says so.  Records drain at
  the tick barrier in (shard, plane) order onto the flight journal's
  ``census`` VARIANT key (wall-free, so the variant stream is
  byte-equal across same-seed reruns — unlike ``walls``/``perf``).

- **Hot-set census** (:class:`CensusTracker`): per-tenant last-served
  tick and a served-span EWMA (decay :data:`CENSUS_EWMA_DECAY` per
  tick, applied lazily so updates stay O(served)).  At each census
  tick it reports hot-set-size-at-decay-threshold curves (how many
  tenants were served within the last N ticks, for each
  ``ANOMOD_CENSUS_DECAY_TICKS`` threshold), a fitted Zipf
  rank-frequency skew estimate (:func:`fit_zipf` over cumulative
  served spans — the power-law design point, PAPERS.md arXiv
  1312.3020), the resident-vs-registered occupancy ratio, and a
  coldest-K eviction-candidate preview — promoted from observed-only
  to the tiering demotion policy's actual input (one shared ordering,
  :meth:`CensusTracker.coldest_candidates`; preview schema unchanged).
  Everything here derives from coordinator-side admission decisions,
  so the hot-set doc is CANONICAL: identical across shard counts,
  pipeline depths, residencies and elastic scaling episodes.

- **Cost attribution** (:func:`fleet_probe`): a registered-fleet sweep
  — engines with registered ∈ ``ANOMOD_CENSUS_SWEEP`` tenants (default
  1e3/1e4/1e5) at a fixed ~1e3-tenant hot traffic set — fitting
  per-tick wall and resident-bytes slopes vs the registered count
  (:func:`fit_slope`).  Today several per-tick costs walk the FULL
  registered fleet (the flight recorder's admission totals, the SLO
  registry, the census's own sweep) and the committed slopes are the
  O(registered) baseline the tiering PR must flatten toward
  O(hot-set); ``anomod census diff`` (:func:`diff_census`) is the
  before/after judge — byte counts compared exactly (they are
  deterministic, so any delta is real), slope fits within the explicit
  box noise tolerance.

The bench ``census`` block (bench.py --mode serve) commits one capture
of all three, plus ONE informational ``process_resident_memory_bytes``
sample read from /proc (a cross-check that the deterministic total is
the right order of magnitude — never a pin, never compared).
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: census-timeline document format (the `anomod census record` dump)
CENSUS_FORMAT = 1

#: the census plane names, in the (shard, plane) drain order's plane
#: axis — one row per (shard, plane) per census tick
CENSUS_PLANES = ("admission", "flight", "perf", "pool", "rca",
                 "scratch", "slo", "tier")

#: per-tick decay of the served-span EWMA (applied lazily per idle
#: tick, so updates stay O(served) and reads O(reported))
CENSUS_EWMA_DECAY = 0.9

# ---------------------------------------------------------------------------
# nominal bookkeeping entry sizes (documented LOGICAL bytes)
#
# Array planes are priced exactly (shape × itemsize).  Python-object
# bookkeeping (dict entries, heap tuples, dataclass rows) is priced at
# the nominal per-entry sizes below — deterministic functions of
# container LENGTH, which is what the census is for: it prices GROWTH
# (does this structure scale with registered or with active tenants?),
# not CPython malloc details.  The /proc RSS sample in the bench block
# is the order-of-magnitude cross-check; these constants are the
# comparable, replayable surface.
# ---------------------------------------------------------------------------

#: one queued micro-batch's bookkeeping beyond its span arrays: the
#: QueuedBatch row (7 fields), its _alive dict entry and its two heap
#: tuples (drain + evict)
QUEUE_ENTRY_BYTES = 224

#: per ACTIVE (ever-offered) tenant in the admission plane: the
#: TenantCounters row (8 ints) and the backlog / last-finish
#: bookkeeping dict entries — all LAZY since the tiering PR (created on
#: a tenant's first offer), so this prices the active set.  The
#: per-REGISTERED remainder is the columnar spec table, priced exactly
#: from its array bytes (:meth:`anomod.serve.queues.AdmissionController.
#: spec_table_nbytes`).
ADMISSION_TENANT_BYTES = 256

#: one lazily-deleted heap tuple (3 slots + tuple header)
HEAP_ENTRY_BYTES = 48

#: per-tenant SLO bookkeeping beyond the digest arrays and the sample
#: buffer: the _TenantSLO row + its dict entry
SLO_TENANT_BYTES = 128

#: per-tenant RCA evidence bookkeeping beyond the buffered span
#: arrays: the buffer list + high-water dict entries
RCA_TENANT_BYTES = 112

#: one flight tick record's nominal retained size (the ring holds dict
#: records whose serialized size varies with topology and wall floats;
#: the census prices the RING LENGTH at this schema-derived nominal so
#: the byte stream stays deterministic)
FLIGHT_RECORD_BYTES = 2048

#: one retained perf-timeline event: len(EVENT_FIELDS)=14 slots of
#: 8 bytes plus dict overhead (anomod.obs.perf.EVENT_FIELDS)
PERF_EVENT_BYTES = 256

#: one warm-tier entry's bookkeeping beyond its exact state arrays:
#: the dict entry, the record row and the detector-snapshot scaffolding
#: (anomod.serve.tiering — alert rows inside the snapshot are already
#: O(alerts), not per-tenant, and stay unpriced like the detector's own)
TIER_WARM_ENTRY_BYTES = 192

#: one cold-tier index entry: the content-address key string (64 hex
#: chars) + its dict entry + the retained scalar meta
TIER_COLD_INDEX_BYTES = 160

def plane_nbytes(arr) -> int:
    """Exact byte size of one array plane from shape × itemsize —
    works for numpy and jax arrays alike (never touches the data)."""
    return math.prod(arr.shape) * int(np.dtype(arr.dtype).itemsize)


#: exact bytes per span row across the 9 SpanBatch columns
#: (anomod.schemas: trace/parent/service/endpoint int32, start/duration
#: int64, is_error bool, status int16, kind int8) — derived from the
#: schema dtypes once so the per-queued-batch census walk is O(1) per
#: batch; pinned equal to the per-array sum in tests/test_census.py
SPAN_ROW_BYTES = (4 * np.dtype(np.int32).itemsize
                  + 2 * np.dtype(np.int64).itemsize
                  + np.dtype(np.bool_).itemsize
                  + np.dtype(np.int16).itemsize
                  + np.dtype(np.int8).itemsize)


def span_batch_nbytes(batch) -> int:
    """Exact byte size of a SpanBatch's column arrays (the string
    tables are shared interned tuples and deliberately excluded):
    ``n_spans × SPAN_ROW_BYTES`` — the schema is fixed-width, so the
    per-row constant IS the per-array sum (pinned)."""
    return batch.n_spans * SPAN_ROW_BYTES


def pool_slot_nbytes(cfg) -> int:
    """Per-slot bytes of one tenant's replay state: the [SW, F] f32
    agg row plus the [SW, H] f32 hist row — the SAME shape whether the
    state lives in a device pool slot or a host-seam pytree."""
    from anomod.replay import N_FEATS
    return cfg.sw * (N_FEATS + cfg.n_hist_buckets) * 4


def tdigest_nbytes(digest) -> int:
    if digest is None:
        return 0
    return plane_nbytes(digest.mean) + plane_nbytes(digest.weight)


def process_resident_bytes() -> Optional[int]:
    """ONE informational RSS sample from /proc/self/statm — the
    order-of-magnitude cross-check the bench block records beside the
    deterministic census total.  Never a pin, never compared (it moves
    with allocator behavior, jax runtime buffers and import history);
    None where /proc is unavailable."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


# ---------------------------------------------------------------------------
# resident-bytes accounting (the per-tick census drain)
# ---------------------------------------------------------------------------

def collect_resident_bytes(engine) -> Tuple[List[dict], Dict[str, int],
                                            int, bool]:
    """One deterministic resident-bytes census of a live ServeEngine.

    Returns ``(planes, by_plane, total_bytes, pool_reconciled)`` where
    ``planes`` is the per-(shard, plane) record list in (shard, plane)
    order (coordinator-owned planes use shard ``-1``), ``by_plane``
    sums bytes per plane name, and ``pool_reconciled`` is the pin that
    every state pool's array bytes equal ``(capacity + 1) × per-slot
    nbytes`` exactly.  A pure read: no clocks, no RNG, no mutation —
    the same engine state always censuses to the same bytes."""
    planes: List[dict] = []
    reconciled = True
    cfg = engine.cfg
    slot_b = pool_slot_nbytes(cfg)

    # tenant states: device pools per shard runner, or the host seam's
    # per-tenant pytrees (same per-slot shape — counted per owned
    # resident replay, NEVER read through .state: a pooled gather
    # would copy megabytes for a byte count the shapes already give)
    owned: Dict[int, int] = {}
    for tid in engine._tenant_replay:
        s = engine.shard_of.get(tid, 0)
        owned[s] = owned.get(s, 0) + 1
    for s, runner in enumerate(engine._runners):
        pool = runner.pool
        if pool is not None:
            arr_b = plane_nbytes(pool.agg) + plane_nbytes(pool.hist)
            expect = (pool.capacity + 1) * slot_b
            ok = arr_b == expect
            reconciled = reconciled and ok
            planes.append({"shard": s, "plane": "pool",
                           "mode": "device", "bytes": arr_b,
                           "slots_used": int(pool.live_slots),
                           "capacity": int(pool.capacity),
                           "slot_bytes": slot_b, "reconciled": ok})
        else:
            n = owned.get(s, 0)
            planes.append({"shard": s, "plane": "pool", "mode": "host",
                           "bytes": n * slot_b, "slots_used": n,
                           "capacity": n, "slot_bytes": slot_b,
                           "reconciled": True})
        scratch_b = 0
        n_bufs = 0
        for slot in runner._lane_scratch.values():
            for buf in slot.values():
                scratch_b += plane_nbytes(buf)
                n_bufs += 1
        planes.append({"shard": s, "plane": "scratch",
                       "bytes": scratch_b, "buffers": n_bufs})

    # admission (coordinator): queued span arrays exact + the columnar
    # spec table's array bytes exact (the per-REGISTERED remainder) +
    # per-ACTIVE bookkeeping at nominal entry sizes — the lazification
    # that collapsed the committed 384 B/registered baseline
    adm = engine.admission
    alive = list(adm._alive.values())
    queued_b = sum(span_batch_nbytes(qb.spans) for qb in alive) \
        + len(alive) * QUEUE_ENTRY_BYTES
    heap_b = (len(adm._drain_heap) + len(adm._evict_heap)) \
        * HEAP_ENTRY_BYTES
    reg_b = adm.spec_table_nbytes()
    active_b = len(adm.counters) * ADMISSION_TENANT_BYTES
    planes.append({"shard": -1, "plane": "admission",
                   "bytes": queued_b + heap_b + reg_b + active_b,
                   "queued_batches": len(alive),
                   "queued_spans": int(adm.backlog_spans),
                   "queued_bytes": queued_b,
                   "registered": len(adm.specs),
                   "registered_bytes": reg_b,
                   "active": len(adm.counters),
                   "active_bytes": active_b})

    # SLO digests (coordinator): one _TenantSLO per tenant that has
    # RECORDED a latency (lazy since the tiering PR — an O(active)
    # plane; it was built eagerly per registered tenant before)
    slo_b = 0
    n_digests = 0
    for slo in engine._slo.values():
        d = tdigest_nbytes(slo.digest)
        if d:
            n_digests += 1
        slo_b += d + len(slo._buf) * 8 + SLO_TENANT_BYTES
    planes.append({"shard": -1, "plane": "slo", "bytes": slo_b,
                   "tenants": len(engine._slo), "digests": n_digests})

    # tenant-state tier (coordinator): warm entries' state arrays exact
    # (the snapshot copies ARE the resident bytes) + nominal per-entry
    # bookkeeping; cold entries live on disk and are priced as index
    # entries only — that residency drop is the tier's whole point
    tier = getattr(engine, "_tier", None)
    if tier is not None:
        planes.append({"shard": -1, "plane": "tier",
                       "bytes": tier.resident_nbytes(),
                       "warm": tier.n_warm, "cold": tier.n_cold,
                       "warm_state_bytes": tier.warm_state_bytes})

    # RCA evidence buffers: per shard plane, buffered span arrays exact
    for s, plane in enumerate(engine._rca_planes):
        rca_b = 0
        n_batches = 0
        for buf in plane._buf.values():
            for b in buf:
                rca_b += span_batch_nbytes(b)
                n_batches += 1
        rca_b += len(plane._buf) * RCA_TENANT_BYTES
        planes.append({"shard": s, "plane": "rca", "bytes": rca_b,
                       "tenants": len(plane._buf),
                       "batches": n_batches})

    # recorder retentions (coordinator): container length × nominal
    # record size (deterministic — the serialized records themselves
    # carry wall floats whose width varies run to run)
    fr = engine.flight_recorder
    n_rec = len(fr.records()) if fr is not None else 0
    planes.append({"shard": -1, "plane": "flight",
                   "bytes": n_rec * FLIGHT_RECORD_BYTES,
                   "records": n_rec})
    n_ev = len(engine.perf_events)
    planes.append({"shard": -1, "plane": "perf",
                   "bytes": n_ev * PERF_EVENT_BYTES, "events": n_ev})

    planes.sort(key=lambda r: (r["shard"], r["plane"]))
    by_plane: Dict[str, int] = {}
    for r in planes:
        by_plane[r["plane"]] = by_plane.get(r["plane"], 0) + r["bytes"]
    total = sum(by_plane.values())
    return planes, by_plane, total, reconciled


# ---------------------------------------------------------------------------
# hot-set census
# ---------------------------------------------------------------------------

class CensusTracker:
    """Coordinator-side hot-set bookkeeping: per-tenant last-served
    tick, cumulative served spans and a lazily-decayed served-span
    EWMA.  ``observe`` is O(served batches) per tick; the census doc
    (:meth:`hot_doc`) walks only ever-served tenants.  Fed ONLY by
    admission's served decisions, so every number here is canonical:
    identical across shard counts, residencies and elastic episodes
    (pinned in tests/test_census.py)."""

    def __init__(self, decay_ticks: Sequence[int], coldest_k: int,
                 every: int):
        self.decay_ticks = tuple(int(t) for t in decay_ticks)
        self.coldest_k = int(coldest_k)
        self.every = int(every)
        self.last_served: Dict[int, int] = {}
        self.served_total: Dict[int, int] = {}
        self._ewma: Dict[int, float] = {}

    def observe(self, tick: int, served) -> None:
        """Fold one tick's served batches (the tick-barrier hook)."""
        per_tenant: Dict[int, int] = {}
        for qb in served:
            per_tenant[qb.tenant_id] = \
                per_tenant.get(qb.tenant_id, 0) + qb.n_spans
        for tid, n in per_tenant.items():
            self._ewma[tid] = self.ewma_at(tid, tick) + float(n)
            self.last_served[tid] = tick
            self.served_total[tid] = self.served_total.get(tid, 0) + n

    def ewma_at(self, tid: int, tick: int) -> float:
        """The tenant's served-span EWMA decayed to ``tick`` (lazy:
        the stored value is anchored at the tenant's last-served
        tick)."""
        got = self._ewma.get(tid)
        if got is None:
            return 0.0
        gap = max(tick - self.last_served.get(tid, tick), 0)
        return got * CENSUS_EWMA_DECAY ** gap

    def due(self, tick: int) -> bool:
        """Whether ``tick`` (0-based) is a census tick — the flight
        digest-cadence contract."""
        return (tick + 1) % self.every == 0

    def coldest_candidates(self, tick: int,
                           resident: Sequence[int]) -> List[int]:
        """Ever-served RESIDENT tenants, coldest first: oldest
        last-served tick, then the weaker EWMA, then the tenant id.
        THE one eviction ordering — the ``hot_doc`` coldest-K preview
        and the tiering demotion policy (anomod.serve.tiering) both
        read it here, so the preview can never disagree with what the
        policy actually evicts."""
        return sorted(
            (tid for tid in resident if tid in self.last_served),
            key=lambda tid: (self.last_served[tid],
                             self.ewma_at(tid, tick), tid))

    def hot_doc(self, tick: int, registered: int,
                resident: Sequence[int]) -> dict:
        """The hot-set census document (all-canonical content)."""
        hot_by_decay = {
            str(th): sum(1 for t in self.last_served.values()
                         if tick - t <= th)
            for th in self.decay_ticks}
        counts = sorted((c for c in self.served_total.values() if c > 0),
                        reverse=True)
        # coldest-K among RESIDENT tenants — the eviction-candidate
        # preview, and (since the tiering PR) the demotion policy's
        # actual input: one shared ordering, unchanged output schema
        cands = self.coldest_candidates(tick, resident)
        coldest = [{"tenant": int(t),
                    "last_served_tick": int(self.last_served[t]),
                    "idle_ticks": int(tick - self.last_served[t]),
                    "rate_ewma": round(self.ewma_at(t, tick), 6)}
                   for t in cands[:self.coldest_k]]
        n_res = len(list(resident))
        return {"registered": int(registered),
                "ever_served": len(self.last_served),
                "resident": n_res,
                "occupancy_vs_registered":
                    round(n_res / registered, 6) if registered else 0.0,
                "hot_by_decay": hot_by_decay,
                "zipf_alpha": fit_zipf(counts),
                "coldest": coldest}


def fit_zipf(counts: Sequence[int]) -> Optional[float]:
    """Zipf rank-frequency skew: least-squares slope of log(count) vs
    log(rank) over the descending positive counts; returns the alpha
    estimate (``count ∝ rank^-alpha``), or None below 3 points."""
    counts = [c for c in counts if c > 0]
    if len(counts) < 3:
        return None
    r = np.log(np.arange(1, len(counts) + 1, dtype=np.float64))
    c = np.log(np.asarray(sorted(counts, reverse=True), np.float64))
    slope = np.polyfit(r, c, 1)[0]
    return round(float(-slope), 6)


def fit_slope(xs: Sequence[float],
              ys: Sequence[float]) -> Tuple[float, float]:
    """Least-squares ``(slope, intercept)`` of ys over xs (float64)."""
    a, b = np.polyfit(np.asarray(xs, np.float64),
                      np.asarray(ys, np.float64), 1)
    return float(a), float(b)


# ---------------------------------------------------------------------------
# cost attribution: the registered-fleet sweep
# ---------------------------------------------------------------------------

def fleet_probe(sizes: Optional[Sequence[int]] = None, hot: int = 1000,
                ticks: int = 8, tick_s: float = 1.0,
                capacity_spans_per_s: float = 2000.0, seed: int = 0,
                n_services: int = 4, warmup_ticks: int = 2,
                tier_hot: Optional[int] = None,
                tier_demote_after: Optional[int] = None) -> dict:
    """The registered-fleet sweep: engines with ``registered`` tenants
    (``sizes``; default ``ANOMOD_CENSUS_SWEEP``) but a FIXED ``hot``-
    tenant traffic set, measuring per-tick wall and census resident
    bytes at each size and fitting both slopes vs the registered count.

    The committed slopes are the O(registered) baseline the tiering
    refactor must flatten: today the admission/SLO registries, the
    flight recorder's per-tick totals walk and the pool sizing all
    scale with REGISTERED tenants even when only ``hot`` of them ever
    offer a span.  Host-seam state + score=False keep the probe about
    the bookkeeping planes (detector scoring is O(served) and already
    active-sized); wall medians drop ``warmup_ticks`` leading ticks.
    ``tier_hot``/``tier_demote_after`` run the sweep with the
    tenant-state tiering plane on (the TIERED capture's sweep —
    demotion active, so the pool plane stays hot-bounded too).
    """
    from anomod.config import get_config
    from anomod.replay import ReplayConfig
    from anomod.serve.engine import ServeEngine
    from anomod.serve.queues import TenantSpec
    from anomod.serve.traffic import PowerLawTraffic
    sizes = [int(s) for s in
             (sizes if sizes is not None else get_config().census_sweep)]
    if int(ticks) < 1:
        raise ValueError("fleet_probe needs ticks >= 1 (zero measured "
                         "ticks would fit a slope over NaN walls)")
    rows: List[dict] = []
    for registered in sizes:
        hot_n = min(int(hot), registered)
        traffic = PowerLawTraffic(
            n_tenants=hot_n,
            total_rate_spans_per_s=float(capacity_spans_per_s),
            alpha=1.2, seed=seed, n_services=n_services)
        specs = list(traffic.specs) + [
            TenantSpec(tenant_id=i, name=f"cold{i:07d}", priority=2)
            for i in range(hot_n, registered)]
        cfg = ReplayConfig(n_services=n_services, n_windows=16,
                           window_us=int(5e6), chunk_size=4096)
        tier_kw = {} if tier_hot is None else dict(
            tier_hot=int(tier_hot),
            tier_demote_after=int(tier_demote_after)
            if tier_demote_after is not None else None)
        eng = ServeEngine(
            specs, traffic.services, cfg,
            capacity_spans_per_s=float(capacity_spans_per_s),
            tick_s=tick_s, buckets=(64, 256), lane_buckets=(1, 2, 4),
            max_backlog=int(8 * capacity_spans_per_s), score=False,
            rca=False, state="host", shards=1, census=True,
            census_every=max(int(ticks), 1), **tier_kw)
        eng.runner.warm()                   # compiles outside the walls
        if eng._fused:
            eng.runner.warm_lanes()
        for _ in range(int(ticks)):
            lo = eng.clock.now_s
            eng.tick(traffic.arrivals(lo, lo + tick_s))
        walls = eng.tick_walls[min(warmup_ticks, len(eng.tick_walls) - 1):]
        resident = eng.census_resident
        rows.append({
            "registered": registered, "hot": hot_n, "ticks": int(ticks),
            "median_tick_wall_s": round(float(np.median(walls)), 6),
            "mean_tick_wall_s": round(float(np.mean(walls)), 6),
            "resident_bytes": resident.get("total", 0),
            "bytes_by_plane": dict(resident.get("by_plane", {})),
            "pool_reconciled": resident.get("pool_reconciled")})
    # the wall slope fits over the per-size MEDIANS: one straggler tick
    # (GC, allocator growth) skews a mean, and the committed baseline
    # must be the robust statistic the docs quote
    wall_slope, wall_icpt = fit_slope(
        sizes, [r["median_tick_wall_s"] for r in rows])
    bytes_slope, bytes_icpt = fit_slope(
        sizes, [r["resident_bytes"] for r in rows])
    return {
        "sizes": sizes, "hot": int(hot), "ticks": int(ticks),
        "seed": int(seed), "rows": rows,
        # the O(registered) baseline curve: seconds of tick wall and
        # resident bytes PER REGISTERED TENANT — what tiering flattens
        "wall_slope_s_per_registered": round(wall_slope, 12),
        "wall_intercept_s": round(wall_icpt, 6),
        "bytes_slope_per_registered": round(bytes_slope, 4),
        "bytes_intercept": round(bytes_icpt, 1),
    }


# ---------------------------------------------------------------------------
# `anomod census diff` — the tiering PR's before/after judge
# ---------------------------------------------------------------------------

def default_slope_tolerance() -> float:
    """Wall-slope comparisons reuse the box noise model the perf
    observatory validated (ANOMOD_PERF_NOISE_FLOOR) — one explicit
    noise hedge for the whole repo, not two."""
    from anomod.config import get_config
    return get_config().perf_noise_floor


def diff_census(a: dict, b: dict,
                tolerance: Optional[float] = None) -> dict:
    """Compare two bench captures' ``census`` blocks.

    BYTE counts are deterministic, so they compare EXACTLY: every
    per-plane delta is real (never noise) and any growth in B is a
    regression.  The bytes SLOPE is a fit over those deterministic
    points, so it compares exactly too.  The WALL slope is wall clock:
    B regresses only when it exceeds A's slope by more than
    ``tolerance`` (default: the ANOMOD_PERF_NOISE_FLOOR box noise
    model).  Returns the verdict document ``anomod census diff``
    prints; ``status`` is ``ok`` / ``bytes-regression`` /
    ``slope-regression`` / ``census-missing``."""
    tol = default_slope_tolerance() if tolerance is None \
        else float(tolerance)
    ca = a.get("census") if isinstance(a.get("census"), dict) else None
    cb = b.get("census") if isinstance(b.get("census"), dict) else None
    if ca is None or cb is None:
        return {"check": "anomod_census_diff",
                "status": "census-missing",
                "missing_in": [side for side, c
                               in (("a", ca), ("b", cb)) if c is None]}
    pa = (ca.get("resident_bytes") or {}).get("by_plane", {})
    pb = (cb.get("resident_bytes") or {}).get("by_plane", {})
    plane_rows = []
    bytes_regressions = []
    for plane in sorted(set(pa) | set(pb)):
        va, vb = pa.get(plane), pb.get(plane)
        row = {"plane": plane, "a": va, "b": vb,
               "delta": (vb - va) if va is not None and vb is not None
               else None}
        plane_rows.append(row)
        if va is not None and vb is not None and vb > va:
            bytes_regressions.append(row)
    sa, sb = ca.get("sweep") or {}, cb.get("sweep") or {}
    # the flat-baseline floor: once tiering SUCCEEDS, the baseline
    # wall slope sits at ~0 (the least-squares fit may even dip
    # negative on noisy walls) and a pure ratio test would never flag
    # the O(registered) cost creeping back.  A regression therefore
    # also flags when B's slope alone would add more than ``tol`` ×
    # A's intercept of wall at the sweep's largest size — scale-aware,
    # so slope noise on a genuinely flat curve stays below it.
    max_size = max(sa.get("sizes") or [0])
    icpt_a = abs(sa.get("wall_intercept_s") or 0.0)
    slope_floor = (tol * icpt_a / max_size) if max_size else float("inf")
    slopes = []
    slope_regressions = []
    for key, exact in (("bytes_slope_per_registered", True),
                       ("wall_slope_s_per_registered", False)):
        va, vb = sa.get(key), sb.get(key)
        if va is None or vb is None:
            continue
        ratio = vb / va if va else None
        if exact:
            regressed = vb > va
        else:
            regressed = vb > max(va, 0.0) * (1.0 + tol) + slope_floor
        row = {"slope": key, "a": va, "b": vb,
               "ratio": round(ratio, 4) if ratio is not None else None,
               "exact": exact, "regressed": bool(regressed)}
        slopes.append(row)
        if regressed:
            slope_regressions.append(row)
    comparable = bool(sa.get("sizes")) and sa.get("sizes") == \
        sb.get("sizes") and sa.get("hot") == sb.get("hot")
    notes = []
    if not comparable:
        notes.append("sweep shapes differ (sizes/hot): slope rows are "
                     "informational, not a verdict")
        slope_regressions = []
    status = ("bytes-regression" if bytes_regressions
              else "slope-regression" if slope_regressions else "ok")
    return {
        "check": "anomod_census_diff",
        "tolerance": tol,
        "note": "byte counts are deterministic — every delta is real; "
                "wall slopes regress only past 1 + tolerance "
                "(ANOMOD_PERF_NOISE_FLOOR, docs/BENCHMARKS.md)",
        "planes": plane_rows,
        "bytes_regressions": bytes_regressions,
        "total_a": (ca.get("resident_bytes") or {}).get("total"),
        "total_b": (cb.get("resident_bytes") or {}).get("total"),
        "slopes": slopes,
        "slope_regressions": slope_regressions,
        "sweep_comparable": comparable,
        "notes": notes,
        "status": status,
    }
