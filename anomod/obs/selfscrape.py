"""Self-scrape scoring: the framework's telemetry through its own detectors.

The dogfood loop the tentpole promises: a run's registry journal exports
to TT-CSV (anomod.obs.export), loads back through the framework's own
``load_tt_metric_csv``, and scores through the UNCHANGED
``OnlineDetector`` stack — each metric subsystem (``serve``, ``ingest``,
``stream``, ``prefetch``...) plays the role of a monitored service, and
a serve-plane stall surfaces exactly the way a slow microservice would:
its latency-shaped samples (tick walls, admission->scored quantiles,
queue-depth gauges) jump, the subsystem's z_latency crosses threshold,
and an Alert names ``serve``.

The metric->span mapping (:func:`spans_from_metrics`) is deliberately
dumb and lossless-enough:

- service  = the metric name's subsystem token (CSV round-trips keep the
  metric name verbatim; series labels do not survive the TT-CSV label
  flattening, so the name carries the routing),
- endpoint = the metric name (so per-endpoint mix shifts are visible to
  the between-window variance the detector already prices),
- duration = the sample value, first differenced per series for
  cumulative ``*_total``/``_count``/``_sum`` streams (Prometheus
  rate-style, so monotone growth cannot masquerade as a latency trend),
  then NORMALIZED to each series' own early-sample scale — one
  subsystem pools metrics whose magnitudes span orders (bytes vs
  seconds vs counts), and without the rescale the pooled variance
  would swallow any single series' shift.

Gauges that sit at exactly their baseline forever contribute nothing —
honest: flat telemetry is not evidence.  What alerts is change.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional

import numpy as np


def spans_from_metrics(batch) -> "object":
    """Synthesize a SpanBatch from a telemetry MetricBatch.

    One span per (finite-valued) sample; see the module docstring for the
    field mapping.  Returns an empty batch when nothing maps.
    """
    from anomod.schemas import KIND_LOCAL, SpanBatch, empty_span_batch
    n = batch.n_samples
    if n == 0:
        return empty_span_batch()
    names = batch.metric_names
    from anomod.obs.registry import subsystem_of
    subsystems: Dict[str, int] = {}
    svc_of_metric = np.zeros(len(names), np.int32)
    counter_like = np.zeros(len(names), bool)
    for i, name in enumerate(names):
        svc_of_metric[i] = subsystems.setdefault(
            subsystem_of(name), len(subsystems))
        # cumulative shapes (counters + histogram count/sum streams)
        counter_like[i] = name.endswith(("_total", "_count", "_sum"))
    finite = np.isfinite(batch.value)
    value = np.where(finite, batch.value, 0.0).astype(np.float64)
    keep = finite.copy()
    # cumulative counters -> per-scrape deltas, per (metric, series) run
    # (journal rows are appended in scrape order, so a stable sort by
    # series+metric keeps each run's time order)
    combo = batch.series.astype(np.int64) * len(names) + batch.metric
    if counter_like.any():
        order = np.argsort(combo, kind="stable")
        cv = combo[order]
        vals = value[order]
        is_ctr = counter_like[batch.metric[order]]
        first = np.ones(len(order), bool)
        first[1:] = cv[1:] != cv[:-1]
        delta = np.empty_like(vals)
        delta[0] = vals[0]
        delta[1:] = vals[1:] - vals[:-1]
        new_vals = np.where(is_ctr, np.maximum(delta, 0.0), vals)
        drop_first = is_ctr & first      # no previous sample to diff from
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        value = new_vals[inv]
        keep &= ~drop_first[inv]
    if not keep.any():
        return empty_span_batch()
    m_idx = batch.metric[keep]
    vals_k = value[keep].copy()
    combo_k = combo[keep]
    # Per-series scale normalization: one subsystem pools metrics whose
    # absolute magnitudes span orders (bytes vs seconds vs counts), and
    # the detector's pooled per-service log-latency variance would
    # swallow any single series' shift.  Each series is rescaled to the
    # median of its first few samples — a healthy series sits near
    # 1e6 "µs" and a 30x stall is a 30x jump on a near-constant
    # baseline, which is exactly the shape z_latency is built for.
    # A series whose early samples are all ~0 (e.g. shed counters before
    # overload) keeps its raw value against the 1e6 anchor: any later
    # activity is then a large positive shift, which is the right read.
    for cv in np.unique(combo_k):
        rows = np.nonzero(combo_k == cv)[0]
        scale = float(np.median(np.abs(vals_k[rows[:5]])))
        vals_k[rows] = vals_k[rows] / scale if scale > 1e-12 \
            else vals_k[rows]
    dur = np.maximum(np.round(vals_k * 1e6), 0.0).astype(np.int64)
    start = np.round(batch.t_s[keep] * 1e6).astype(np.int64)
    order = np.argsort(start, kind="stable")
    n_k = int(keep.sum())
    return SpanBatch(
        trace=np.arange(n_k, dtype=np.int32)[order],
        parent=np.full(n_k, -1, np.int32),
        service=svc_of_metric[m_idx][order],
        endpoint=m_idx.astype(np.int32)[order],
        start_us=start[order], duration_us=dur[order],
        is_error=np.zeros(n_k, np.bool_),
        status=np.zeros(n_k, np.int16),
        kind=np.full(n_k, KIND_LOCAL, np.int8),
        services=tuple(subsystems), endpoints=tuple(names),
        trace_ids=tuple(f"t{i:06x}" for i in range(n_k)),
    ).validate()


def score_self_scrape(source, window_s: float = 5.0,
                      baseline_windows: int = 4, z_threshold: float = 4.0,
                      min_count: float = 3.0, n_windows: int = 64,
                      consecutive: int = 1) -> dict:
    """Score a self-scrape capture with the framework's own detector.

    ``source`` is a TT-CSV path (loaded via the framework's
    ``load_tt_metric_csv`` — the round-trip contract) or a MetricBatch.
    Returns a JSON-able report: per-subsystem alert timeline + verdict.
    """
    from anomod.replay import ReplayConfig
    from anomod.stream import stream_experiment
    if isinstance(source, (str, Path)):
        from anomod.io.metrics import load_tt_metric_csv
        batch = load_tt_metric_csv(Path(source))
        if batch is None:
            raise ValueError(f"not a loadable TT metric CSV: {source}")
    else:
        batch = source
    spans = spans_from_metrics(batch)
    out = {
        "n_samples": int(batch.n_samples),
        "n_metrics": len(batch.metric_names),
        "subsystems": list(spans.services),
        "window_seconds": window_s,
        "n_alerts": 0,
        "alerted_subsystems": [],
        "alerts": [],
    }
    if spans.n_spans == 0:
        return out
    cfg = ReplayConfig(n_services=spans.n_services, n_windows=n_windows,
                       window_us=int(window_s * 1e6), chunk_size=1024)
    # telemetry spans carry no parent links — the edge plane would only
    # triple the replay rows for zero evidence
    det = stream_experiment(spans, cfg=cfg, slice_s=window_s,
                            baseline_windows=baseline_windows,
                            z_threshold=z_threshold, min_count=min_count,
                            consecutive=consecutive,
                            edge_attribution=False)
    alerted = sorted({a.service_name for a in det.alerts})
    out.update(
        n_alerts=len(det.alerts),
        alerted_subsystems=alerted,
        ranked_subsystems=det.ranked_services()[:5],
        alerts=[{"window": a.window, "subsystem": a.service_name,
                 "score": round(a.score, 3),
                 "z_latency": round(a.z_latency, 3),
                 "z_drop_cum": round(a.z_drop_cum, 3),
                 "evidence": a.evidence} for a in det.alerts[:50]])
    return out


def self_exercise(duration_s: float = 20.0, n_tenants: int = 24,
                  capacity_spans_per_s: float = 4000.0, seed: int = 0,
                  registry=None, tracer=None):
    """Drive a short seeded serve run with telemetry on and return the
    registry that observed it — the ``anomod obs`` CLI's way to produce a
    meaningful snapshot/export from a fresh process.  Swaps the given (or
    a fresh, force-enabled) registry in as the process default for the
    run, then restores the previous one.  ``tracer`` (when given) rides
    the engine so the same exercise can feed the span exporters
    (``anomod obs export --format chrome``/``jaeger``)."""
    from anomod.obs.registry import Registry, set_registry
    reg = registry if registry is not None else Registry(enabled=True)
    prev = set_registry(reg)
    try:
        from anomod.serve.engine import run_power_law
        run_power_law(n_tenants=n_tenants, n_services=8,
                      capacity_spans_per_s=capacity_spans_per_s,
                      overload=1.5, duration_s=duration_s, tick_s=0.5,
                      seed=seed, window_s=5.0, baseline_windows=2,
                      fault_tenants=1, tracer=tracer)
    finally:
        set_registry(prev)
    return reg
