"""Registry exporters: Prometheus text format + the framework's own shapes.

Two sinks, one registry:

- :func:`to_prometheus_text` renders the point-in-time state in the
  Prometheus exposition format (``# TYPE`` + samples; histograms as
  summaries with ``quantile`` labels) — the shape every external scraper
  speaks, and the shape the reference testbeds' own monitoring exported
  (fetch_prometheus_metrics.py).
- :func:`to_metric_batch` / :func:`export_tt_csv` materialize the scrape
  JOURNAL (the time series, not the last value) as the framework's own
  ``MetricBatch`` / TT long-CSV shapes — ``write_metric_batch_tt_csv``
  out, ``load_tt_metric_csv`` back — which is what closes the dogfood
  loop: a run's telemetry scores through the same detector stack as any
  monitored SUT (anomod.obs.selfscrape).

The CSV export publishes atomically (same-directory tmp + ``os.replace``,
the anomod.io.cache idiom) so a killed run never leaves a truncated
capture.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, List

import numpy as np

from anomod.obs.registry import Registry, subsystem_of


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render bare."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def escape_label_value(value: str) -> str:
    """Label-value escaping per the exposition-format grammar: inside
    the double quotes, backslash, double-quote and line-feed must render
    as ``\\\\``, ``\\"`` and ``\\n`` — in that order (escaping the
    escape character first, or a value containing ``\\n`` literally
    would round-trip as a newline)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def escape_help_text(text: str) -> str:
    """HELP-line escaping: only backslash and line-feed (the grammar
    leaves double quotes alone outside label position)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def render_prom_labels(labels: Dict[str, str]) -> str:
    """Labels rendered for the exposition format — the escaping twin of
    :func:`anomod.obs.registry.render_labels` (which stays unescaped on
    purpose: its output is the registry's internal series key and the
    TT-CSV export's label string, where a ``\\n`` is just a character).
    Only the text format has a grammar that ``\\``/``"``/newline can
    break out of, so only this renderer escapes."""
    return ",".join(f'{k}="{escape_label_value(v)}"'
                    for k, v in sorted(labels.items()))


def _help_for(m) -> str:
    """One HELP line per metric family: the subsystem token plus the
    kind — derived, so every family (including ones added after this
    writing) gets a parseable, truthful HELP line without a hand-kept
    catalog that would rot."""
    return escape_help_text(
        f"anomod {subsystem_of(m.name)}-subsystem {m.kind}")


def to_prometheus_text(registry: Registry) -> str:
    """Point-in-time registry state in the Prometheus text format
    (``# HELP`` + ``# TYPE`` per family, label values escaped per the
    exposition-format grammar — pinned by an adversarial-label
    round-trip test in tests/test_obs.py)."""
    lines: List[str] = []
    seen: set = set()
    for m in sorted(registry.metrics(),
                    key=lambda m: (m.name, render_prom_labels(m.labels))):
        base = render_prom_labels(m.labels)
        brace = f"{{{base}}}" if base else ""
        # HELP/TYPE are once per metric FAMILY (the grammar allows one
        # each per name): label variants of one name — e.g. the
        # shard-labeled gauge twins — share the header their sorted
        # grouping puts first
        if m.name not in seen:
            seen.add(m.name)
            lines.append(f"# HELP {m.name} {_help_for(m)}")
            lines.append(f"# TYPE {m.name} "
                         f"{'summary' if m.kind == 'histogram' else m.kind}")
        if m.kind == "histogram":
            # t-digest histograms export as Prometheus SUMMARIES: the
            # sketch stores quantiles, not cumulative bucket counts
            p50 = m.quantile(0.5)
            if p50 is not None:
                for q, v in (("0.5", p50), ("0.99", m.quantile(0.99))):
                    ql = render_prom_labels({**m.labels, "quantile": q})
                    lines.append(f"{m.name}{{{ql}}} {_fmt(v)}")
            lines.append(f"{m.name}_sum{brace} {_fmt(m.sum)}")
            lines.append(f"{m.name}_count{brace} {_fmt(m.count)}")
        else:
            lines.append(f"{m.name}{brace} {_fmt(m.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def to_metric_batch(registry: Registry):
    """The scrape journal as a ``MetricBatch``.

    Services are the metric-name subsystems (``anomod_serve_...`` ->
    ``serve``) and every series key carries a ``service="<subsystem>"``
    label alongside the metric's own labels, so the batch drops straight
    into ``MultimodalDetector.push_metrics`` with correct per-service
    attribution — no re-derivation needed on the direct (non-CSV) path.
    """
    return rows_to_metric_batch(registry.journal())


def rows_to_metric_batch(rows):
    """Journal-shaped rows ``(t_s, sample_name, labels_str, value)`` ->
    ``MetricBatch`` — the row-level core of :func:`to_metric_batch`,
    shared with the live feed (anomod.serve.feed), whose rows come off a
    scraped ``/metrics`` endpoint or a Prometheus ``query_range`` poll
    rather than a local registry."""
    from anomod.schemas import MetricBatch
    metric_names: Dict[str, int] = {}
    series_keys: Dict[str, int] = {}
    services: Dict[str, int] = {}
    series_service: List[int] = []
    n = len(rows)
    metric_c = np.zeros(n, np.int32)
    series_c = np.zeros(n, np.int32)
    t_c = np.zeros(n, np.float64)
    v_c = np.zeros(n, np.float64)
    for i, (t_s, name, labels_str, value) in enumerate(rows):
        metric_c[i] = metric_names.setdefault(name, len(metric_names))
        sub = subsystem_of(name)
        key = f'service="{sub}"' + (f",{labels_str}" if labels_str else "")
        if key not in series_keys:
            series_keys[key] = len(series_keys)
            series_service.append(
                services.setdefault(sub, len(services)))
        series_c[i] = series_keys[key]
        t_c[i] = t_s
        v_c[i] = value
    return MetricBatch(
        metric=metric_c, series=series_c, t_s=t_c, value=v_c,
        metric_names=tuple(metric_names), series_keys=tuple(series_keys),
        series_service=np.asarray(series_service or [0],
                                  np.int32)[:len(series_keys)],
        services=tuple(services))


def export_prometheus_text(registry: Registry, path) -> int:
    """Write the point-in-time Prometheus text view (atomic publish);
    returns the number of metrics rendered."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    try:
        tmp.write_text(to_prometheus_text(registry))
        os.replace(tmp, path)
    finally:
        if tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass
    return len(registry.metrics())


def export_tt_csv(registry: Registry, path) -> int:
    """Write the scrape journal in the TT long-CSV shape (atomic publish);
    returns the number of samples written.

    The file round-trips through ``anomod.io.metrics.load_tt_metric_csv``
    — the framework's own loader — which is the self-scrape contract the
    scorer (anomod.obs.selfscrape) and the committed bench capture rely
    on."""
    from anomod.io.metrics import write_metric_batch_tt_csv
    batch = to_metric_batch(registry)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    try:
        write_metric_batch_tt_csv(batch, tmp)
        os.replace(tmp, path)
    finally:
        if tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass
    return batch.n_samples
