"""anomod.obs — the framework's self-scraping telemetry plane.

A process-wide metrics registry (Counter / Gauge / t-digest Histogram,
anomod.obs.registry), two exporters (Prometheus text + the framework's
own MetricBatch / TT-CSV, anomod.obs.export), and the dogfood loop that
scores a run's own telemetry through the unchanged detector stack
(anomod.obs.selfscrape).  See docs/OBSERVABILITY.md for the metric
catalog and the self-scrape recipe.

Instrumented call sites use the module-level helpers::

    from anomod import obs
    obs.counter("anomod_ingest_cache_hits_total").inc()
    obs.gauge("anomod_serve_backlog_spans").set(depth)
    obs.histogram("anomod_serve_tick_seconds").observe(wall)

Handles are memoized by (name, labels); with ``ANOMOD_OBS_ENABLED=0``
every helper returns a shared no-op handle.
"""

from anomod.obs.registry import (NULL, Counter, Gauge, Histogram, Registry,
                                 get_registry, render_labels, set_registry,
                                 subsystem_of)

__all__ = ["NULL", "Counter", "Gauge", "Histogram", "Registry",
           "get_registry", "set_registry", "render_labels", "subsystem_of",
           "counter", "gauge", "histogram", "scrape"]


def counter(name: str, **labels) -> Counter:
    return get_registry().counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return get_registry().gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return get_registry().histogram(name, **labels)


def scrape(now_s=None) -> int:
    return get_registry().scrape(now_s)
