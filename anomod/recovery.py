"""Failure detection + elastic recovery — the reference's self-healing layer.

The reference keeps a chaos-battered cluster collectable with four pieces of
recovery machinery (SURVEY §5):

- ``wait_for_pods_ready`` (run_experiment.sh:147-258): poll pod phases until
  every pod is Ready; **force-delete** pods stuck in CrashLoopBackOff /
  Error / ImagePullBackOff so their ReplicaSet respawns them; pods that sit
  *Running but not Ready* past a stuck deadline (180 s) get restarted too;
  give up at a global timeout.
- Prometheus OOM guard (run_experiment.sh:416-455): before each run, restart
  the Prometheus deployment if its pod was OOMKilled / is unready, then wait
  for it to come back.
- ERR/EXIT traps (run_experiment.sh:407-411, run_all_experiments.sh:12-30,
  automated_multimodal_collection.sh:13-39): any failure path destroys the
  active chaos experiments before the process exits.
- Pre-run sweeps (run_all_experiments.sh:169-217): destroy *all* leftover
  ChaosBlade/Chaos-Mesh experiments from previous crashed runs.

Here those behaviors are a deterministic, tick-based controller over a
synthetic pod cluster (no wall-clock sleeps — a virtual clock advances in
poll intervals), so recovery policy is unit-testable: seeded failure
scenarios (slow starters, crash-loopers, stuck-not-ready pods, OOM-killed
Prometheus) must converge to all-Ready within the modeled deadlines exactly
as the reference's bash loops would.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from anomod.chaos import ChaosController


class Phase(enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    CRASHLOOP = "CrashLoopBackOff"
    ERROR = "Error"
    IMAGEPULL = "ImagePullBackOff"


#: phases the reference force-deletes on sight (run_experiment.sh:177-199
#: greps for CrashLoopBackOff|Error|ImagePullBackOff and deletes --force)
FORCE_DELETE_PHASES = (Phase.CRASHLOOP, Phase.ERROR, Phase.IMAGEPULL)


@dataclasses.dataclass
class Pod:
    """One pod's deterministic lifecycle script.

    ``startup_s`` — virtual seconds from (re)creation until Running+Ready.
    ``crashloop`` — if True the pod enters CrashLoopBackOff instead of
    Running until it has been force-deleted ``crashes_before_ok`` times
    (modeling the transient image/init failures the reference recovers from
    by deletion-respawn).
    ``stuck_unready`` — if True the pod reaches Running but never flips
    Ready until restarted once (the Running-not-Ready hang the reference
    restarts after 180 s).
    """
    name: str
    service: str
    startup_s: float = 20.0
    crashloop: bool = False
    crashes_before_ok: int = 1
    stuck_unready: bool = False
    # mutable runtime state
    created_at: float = 0.0
    restarts: int = 0
    deletions: int = 0

    def phase_at(self, t: float) -> Tuple[Phase, bool]:
        """(phase, ready) at virtual time ``t``."""
        age = t - self.created_at
        if self.crashloop and self.deletions < self.crashes_before_ok:
            return (Phase.PENDING, False) if age < 5.0 else (Phase.CRASHLOOP, False)
        if age < self.startup_s:
            return Phase.PENDING, False
        if self.stuck_unready and self.restarts == 0:
            return Phase.RUNNING, False
        return Phase.RUNNING, True


class SyntheticCluster:
    """A deterministic pod set driven by a virtual clock.

    ``delete_pod`` models `kubectl delete pod --force --grace-period=0`: the
    ReplicaSet immediately respawns the pod with a fresh creation time
    (run_experiment.sh:186-199); crash-loopers count deletions and come up
    clean once the scripted number of respawns has happened.
    """

    def __init__(self, pods: Iterable[Pod], t0: float = 0.0) -> None:
        self.now = t0
        self.pods: Dict[str, Pod] = {}
        for p in pods:
            p.created_at = t0
            self.pods[p.name] = p

    def advance(self, dt: float) -> None:
        self.now += dt

    def snapshot(self) -> Dict[str, Tuple[Phase, bool]]:
        return {n: p.phase_at(self.now) for n, p in self.pods.items()}

    def delete_pod(self, name: str) -> None:
        p = self.pods[name]
        p.deletions += 1
        p.created_at = self.now          # respawned by the ReplicaSet
        if p.stuck_unready:
            p.restarts += 1

    def restart_pod(self, name: str) -> None:
        """Model `kubectl delete pod` on a Running pod (graceful restart)."""
        self.delete_pod(name)


def cluster_for_testbed(testbed: str, seed: int = 0,
                        n_slow: int = 2, n_crashloop: int = 1,
                        n_stuck: int = 1) -> SyntheticCluster:
    """A seeded cluster over the testbed's service table with a deterministic
    sprinkling of the three failure archetypes the reference recovers from."""
    from anomod.synth import SN_SERVICES, TT_SERVICES
    services = SN_SERVICES if testbed == "SN" else TT_SERVICES
    if n_slow + n_crashloop + n_stuck > len(services):
        raise ValueError(
            f"{n_slow + n_crashloop + n_stuck} troubled pods requested but "
            f"{testbed} has only {len(services)} services")
    pods: List[Pod] = []
    order = sorted(services, key=lambda s: hashlib.sha1(
        f"{seed}:{s}".encode()).hexdigest())
    troubled = {s: kind
                for s, kind in zip(order, ["slow"] * n_slow
                                   + ["crashloop"] * n_crashloop
                                   + ["stuck"] * n_stuck)}
    for svc in services:
        suffix = hashlib.sha1(f"{seed}:{svc}:pod".encode()).hexdigest()[:5]
        kind = troubled.get(svc)
        pods.append(Pod(
            name=f"{svc}-{suffix}", service=svc,
            startup_s=90.0 if kind == "slow" else 20.0,
            crashloop=kind == "crashloop",
            stuck_unready=kind == "stuck"))
    return SyntheticCluster(pods)


@dataclasses.dataclass
class ReadinessReport:
    ready: bool
    waited_s: float
    polls: int
    force_deleted: List[str]
    restarted_stuck: List[str]
    unready_at_timeout: List[str]


class ReadinessController:
    """The ``wait_for_pods_ready`` policy as a reusable controller.

    Defaults mirror the reference: 10 s poll interval, 180 s stuck deadline,
    600 s global timeout (run_experiment.sh:147-258 — its loop polls every
    10 s, tracks `not_ready_since` per pod, and bails after the deadline).
    """

    def __init__(self, poll_s: float = 10.0, stuck_deadline_s: float = 180.0,
                 timeout_s: float = 600.0) -> None:
        self.poll_s = poll_s
        self.stuck_deadline_s = stuck_deadline_s
        self.timeout_s = timeout_s

    def wait_for_pods_ready(self, cluster: SyntheticCluster) -> ReadinessReport:
        t_start = cluster.now
        not_ready_since: Dict[str, float] = {}
        force_deleted: List[str] = []
        restarted: List[str] = []
        polls = 0
        while True:
            polls += 1
            snap = cluster.snapshot()
            unready = [n for n, (_, ok) in snap.items() if not ok]
            if not unready:
                return ReadinessReport(True, cluster.now - t_start, polls,
                                       force_deleted, restarted, [])
            for name in unready:
                phase, _ = snap[name]
                if phase in FORCE_DELETE_PHASES:
                    cluster.delete_pod(name)
                    force_deleted.append(name)
                    not_ready_since.pop(name, None)
                    continue
                if phase is not Phase.RUNNING:
                    # deadline counts Running-not-Ready time only, not Pending
                    not_ready_since.pop(name, None)
                    continue
                since = not_ready_since.setdefault(name, cluster.now)
                if cluster.now - since >= self.stuck_deadline_s:
                    cluster.restart_pod(name)
                    restarted.append(name)
                    not_ready_since[name] = cluster.now
            if cluster.now - t_start >= self.timeout_s:
                snap = cluster.snapshot()
                return ReadinessReport(
                    False, cluster.now - t_start, polls, force_deleted,
                    restarted, [n for n, (_, ok) in snap.items() if not ok])
            cluster.advance(self.poll_s)


# ---------------------------------------------------------------------------
# Prometheus OOM guard (run_experiment.sh:416-455)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PrometheusState:
    """The monitoring pod the reference restarts between runs because long
    24 h PromQL ranges OOM it (run_all_experiments.sh:316-355)."""
    oom_killed: bool = False
    ready: bool = True
    restart_count: int = 0
    startup_s: float = 30.0
    restarted_at: Optional[float] = None

    def needs_restart(self) -> bool:
        return self.oom_killed or not self.ready


def guard_prometheus(state: PrometheusState, cluster: SyntheticCluster,
                     poll_s: float = 10.0, timeout_s: float = 300.0) -> bool:
    """Restart-if-unhealthy then wait-until-ready.  Returns readiness."""
    if state.needs_restart():
        state.restart_count += 1
        state.oom_killed = False
        state.ready = False
        state.restarted_at = cluster.now
    waited = 0.0
    while not state.ready and waited < timeout_s:
        cluster.advance(poll_s)
        waited += poll_s
        if (state.restarted_at is not None
                and cluster.now - state.restarted_at >= state.startup_s):
            state.ready = True
    return state.ready


# ---------------------------------------------------------------------------
# Guarded runs: trap-equivalent chaos teardown + pre-run sweep
# ---------------------------------------------------------------------------

class GuardedRun:
    """Context manager with the reference's trap semantics.

    On entry: pre-run sweep destroys every leftover chaos experiment
    (run_all_experiments.sh:169-217, cleanup_all_previous_anomalies).  On
    exit — **including exceptions**, the ERR/EXIT trap path — all chaos
    created during the run is destroyed.
    """

    def __init__(self, controller: ChaosController) -> None:
        self.controller = controller
        self.swept_on_entry = 0

    def __enter__(self) -> "GuardedRun":
        self.swept_on_entry = self.controller.destroy_all()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.controller.destroy_all()


def run_with_recovery(cluster: SyntheticCluster,
                      controller: ChaosController,
                      label_or_name,
                      body: Callable[[], object],
                      prometheus: Optional[PrometheusState] = None,
                      readiness: Optional[ReadinessController] = None,
                      ) -> Tuple[object, ReadinessReport]:
    """One experiment with the full recovery envelope, in reference order:
    sweep leftovers → Prometheus guard → wait for pods → inject → body →
    teardown (guaranteed).  Raises if the cluster never becomes ready, like
    run_experiment.sh aborting the run."""
    readiness = readiness or ReadinessController()
    with GuardedRun(controller):
        if prometheus is not None:
            if not guard_prometheus(prometheus, cluster):
                raise RuntimeError("prometheus did not recover")
        report = readiness.wait_for_pods_ready(cluster)
        if not report.ready:
            raise RuntimeError(
                f"pods not ready after {report.waited_s:.0f}s: "
                f"{report.unready_at_timeout}")
        with controller.inject(label_or_name):
            result = body()
    return result, report
