"""Campaign runner + dataset materializer — the collection-toolchain analog.

The reference's orchestrators run 13 experiments per testbed and archive five
modalities per experiment under a naming convention
(automated_multimodal_collection.sh:787-891; run_all_experiments.sh:549-598;
layout at collect_all_data.sh:207-211 and T-Dataset/README.md:9-17).  This
module reproduces that pipeline against the synthetic SUT: each "run" injects
a fault (by conditioning the generator), "collects" all modalities, and
archives them in the exact reference tree shape, so the output directory is a
drop-in SN_data/TT_data replacement with materialized payloads (no LFS stubs):

  SN: <out>/SN_data/{log,metric,trace,coverage}_data/<Exp>_<ts>_<modality>_<ts2>/
      + api_responses/<Exp>_<ts>_openapi_<ts2>/openapi_responses.jsonl
  TT: <out>/TT_data/{log,metric,trace,api_responses,coverage_report}/<Exp>_<ts>_em/

Timestamps are derived deterministically from the experiment seed so trees are
reproducible.
"""

from __future__ import annotations

import datetime as dt
import json
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

from anomod import labels as labels_mod
from anomod import synth
from anomod.io.api import write_api_jsonl
from anomod.io.metrics import write_metric_batch_tt_csv
from anomod.schemas import Experiment, LOG_ERROR, LOG_INFO, LOG_WARN

_BASE_TS = dt.datetime(2026, 1, 5, 12, 0, 0)


def _ts_for(name: str, style: str) -> str:
    off = int(synth._seed_for(name, 9) % 86_400)
    t = _BASE_TS + dt.timedelta(seconds=off)
    if style == "sn":
        return t.strftime("%Y%m%d_%H%M%S")
    if style == "sn2":
        return t.strftime("%Y-%m-%d_%H-%M-%S")
    return t.strftime("%Y%m%dT%H%M%SZ")  # tt


def _write_log_text(exp: Experiment, svc_idx: int, path: Path) -> dict:
    """Render a plausible log file from the LogBatch lines of one service."""
    lvl_name = {LOG_INFO: "INFO", LOG_WARN: "WARN", LOG_ERROR: "ERROR"}
    rows = np.flatnonzero(exp.logs.service == svc_idx)
    lines = []
    for r in rows:
        t = dt.datetime.fromtimestamp(float(exp.logs.t_s[r]), dt.timezone.utc)
        lvl = lvl_name.get(int(exp.logs.level[r]), "DEBUG")
        lines.append(f"{t.strftime('%Y-%m-%d %H:%M:%S')} {lvl} "
                     f"{exp.logs.services[svc_idx]}: request handled")
    path.write_text("\n".join(lines) + ("\n" if lines else ""))
    lvls = exp.logs.level[rows]
    return {"lines": len(rows),
            "errors": int((lvls == LOG_ERROR).sum()),
            "warnings": int((lvls == LOG_WARN).sum())}


def _materialize_sn(exp: Experiment, label, root: Path) -> None:
    ts1, ts2 = _ts_for(exp.name, "sn"), _ts_for(exp.name, "sn2")
    base = f"{label.experiment}_{ts1}"

    # traces: all_traces.json + csv-ish flat export
    tdir = root / "trace_data" / f"{base}_traces_{ts2}"
    tdir.mkdir(parents=True, exist_ok=True)
    doc = synth.spans_to_jaeger_json(exp.spans)
    (tdir / "all_traces.json").write_text(json.dumps(doc))
    from anomod.io.sn_traces import write_jaeger_csv
    write_jaeger_csv(exp.spans, tdir / "all_traces.csv")
    (tdir / "available_services.json").write_text(json.dumps(
        {"data": sorted(set(exp.spans.services)), "total": exp.spans.n_services}))

    # metrics: per-metric CSVs (timestamp,value,metric + label columns)
    mdir = root / "metric_data" / f"{base}_metrics_{ts2}"
    mdir.mkdir(parents=True, exist_ok=True)
    m = exp.metrics
    for mi, mname in enumerate(m.metric_names):
        rows = np.flatnonzero(m.metric == mi)
        with open(mdir / f"{mname}.csv", "w") as f:
            f.write("timestamp,value,metric\n")
            for r in rows:
                t = dt.datetime.fromtimestamp(float(m.t_s[r]))
                f.write(f"{t},{m.value[r]},\"{m.series_keys[int(m.series[r])]}\"\n")
    # window line follows the reference's app-start discovery + clamp
    # semantics (metric_collector.py:480-525) — pod start = first sample
    from anomod.metrics_catalog import experiment_window, fmt_window
    w0, w1 = experiment_window([float(m.t_s.min())] if m.n_samples else None,
                               float(m.t_s.max()) if m.n_samples else 0.0)
    (mdir / "metadata.txt").write_text(
        f"experiment: {exp.name}\nqueries: {len(m.metric_names)}\n"
        f"step: 15s\nwindow: {fmt_window(w0, w1)}\n")

    # logs: <Service>_<ts>.log + summary.txt (collect_log.sh:113-137 shape)
    ldir = root / "log_data" / f"{base}_logs_{ts2}"
    ldir.mkdir(parents=True, exist_ok=True)
    summary_lines = [f"Collection timestamp: {ts1}",
                     "Time window: full history",
                     f"Services captured: {len(exp.logs.services)}", "",
                     "Log file summary:"]
    for si, svc in enumerate(exp.logs.services):
        display = "".join(w.capitalize() for w in svc.split("-"))
        stats = _write_log_text(exp, si, ldir / f"{display}_{ts1}.log")
        summary_lines.append(
            f"- {display}: {stats['lines']*90//1024}K ({stats['lines']} lines) | "
            f"errors={stats['errors']}, warnings={stats['warnings']}, startup=1")
    (ldir / "summary.txt").write_text("\n".join(summary_lines) + "\n")

    # api responses (enhanced_openapi_monitor.py output family)
    from anomod.io.api import write_api_artifact_family
    write_api_artifact_family(
        exp.api, root / "api_responses" / f"{base}_openapi_{ts2}")

    # coverage: per-service gcov text
    cdir = root / "coverage_data" / f"{base}_coverage_{ts2}"
    for fi in range(len(exp.coverage.paths)):
        svc = exp.coverage.services[int(exp.coverage.service[fi])]
        sdir = cdir / svc
        sdir.mkdir(parents=True, exist_ok=True)
        total = int(exp.coverage.lines_total[fi])
        covered = int(exp.coverage.lines_covered[fi])
        src = exp.coverage.paths[fi]
        gname = "#" + src.replace("/", "#") + ".gcov"
        lines = [f"        -:    0:Source:/{src}"]
        for ln in range(1, total + 1):
            cnt = "5" if ln <= covered else "#####"
            lines.append(f"        {cnt}:{ln:5d}:  line_{ln};")
        (sdir / gname).write_text("\n".join(lines) + "\n")


def _materialize_tt(exp: Experiment, label, root: Path) -> None:
    ts = _ts_for(exp.name, "tt")
    base = (f"{label.experiment}_{ts}_em" if label.is_anomaly
            else f"{label.experiment}_em_{ts}")

    tdir = root / "trace_data" / base
    tdir.mkdir(parents=True, exist_ok=True)
    doc = synth.spans_to_skywalking_json(exp.spans, base)
    stamp = ts.replace("T", "_").replace("Z", "")
    (tdir / f"{base}_skywalking_traces_{stamp}.json").write_text(json.dumps(doc))
    # ES-collector analysis artifact alongside the raw traces
    # (enhanced_trace_collector.py's collect-and-analyze pipeline)
    from anomod.io.tt_traces_es import write_trace_analysis
    write_trace_analysis(exp.spans, tdir, timestamp=stamp)

    mdir = root / "metric_data" / base
    mdir.mkdir(parents=True, exist_ok=True)
    write_metric_batch_tt_csv(exp.metrics, mdir / f"{base}_metrics_{stamp}.csv")

    ldir = root / "log_data" / base
    for si, svc in enumerate(exp.logs.services):
        pod = f"{svc}-{synth._seed_for(svc, 1) % 0xfffff:05x}"
        pdir = ldir / pod
        pdir.mkdir(parents=True, exist_ok=True)
        _write_log_text(exp, si, pdir / f"{pod}_{stamp}.log")
    (ldir / f"log_collection_report_{stamp}.json").write_text(json.dumps({
        "experiment": base, "pods": len(exp.logs.services),
        "total_lines": int(exp.logs.n_lines)}))
    (ldir / f"kubernetes_events_{stamp}.json").write_text(json.dumps(
        {"items": []}))

    adir = root / "api_responses" / base / _BASE_TS.strftime("%Y%m%d")
    adir.mkdir(parents=True, exist_ok=True)
    write_api_jsonl(exp.api, adir / "api_responses.jsonl")

    # coverage: per-pod exec-analog dumps + per-service merged report tree
    # (collect_coverage_reports.sh:54-191 pipeline shape)
    from anomod.io.coverage_report import batch_to_dumps, collect_coverage_reports
    dumps = batch_to_dumps(exp.coverage,
                           seed=int(synth._seed_for(exp.name, 13) % 2**31))
    # pod identity must match the log tree's naming (same salt) so modalities
    # correlate by pod the way the reference dataset does
    pods = {f"{d.service}-{synth._seed_for(d.service, 1) % 0xfffff:05x}": [d]
            for d in dumps}
    collect_coverage_reports(pods, root / "coverage_data" / base,
                             root / "coverage_report" / base)


def run_campaign(testbed: str, out_dir: Path,
                 experiments: Optional[Sequence[str]] = None,
                 n_traces: int = 200, seed: Optional[int] = None) -> List[str]:
    """Generate + archive experiments in the reference tree shape.

    The campaign traces ITSELF (generate/materialize spans per experiment,
    anomod.utils.tracing) and archives the trace as
    ``<out>/campaign_trace_<testbed>.json`` in Jaeger shape — the
    framework-level analog of the reference instrumenting its own toolchain
    with Jaeger/SkyWalking, loadable back through anomod.io.sn_traces.  The
    trace is written even when a run fails partway (that is when per-stage
    timings matter most).

    Returns the list of archived experiment dir basenames.
    """
    from anomod.utils.tracing import Tracer

    out_dir = Path(out_dir)
    root = out_dir / f"{testbed}_data"
    chosen = [labels_mod.label_for(e) for e in experiments] if experiments \
        else labels_mod.labels_for_testbed(testbed)
    done = []
    tracer = Tracer(service=f"anomod-campaign-{testbed}")
    try:
        with tracer.span(f"campaign[{testbed}]"):
            for label in chosen:
                if label is None or label.testbed != testbed:
                    raise ValueError(f"bad experiment for {testbed}: {label}")
                with tracer.span(f"experiment[{label.experiment}]"):
                    with tracer.span("generate"):
                        exp = synth.generate_experiment(
                            label, n_traces=n_traces, seed=seed)
                    with tracer.span("materialize"):
                        if testbed == "SN":
                            _materialize_sn(exp, label, root)
                        else:
                            _materialize_tt(exp, label, root)
                done.append(label.experiment)
    finally:
        out_dir.mkdir(parents=True, exist_ok=True)
        tracer.dump(out_dir / f"campaign_trace_{testbed}.json")
    return done
