"""SN API-response monitoring subsystem — active/passive monitors + capture
orchestrator, re-designed as deterministic request programs over the
synthetic SUT.

Reference behavior contracts (all under
``SN_collection-scripts/Dataset/api_responses/``):

- ``enhanced_openapi_monitor.py`` — the *active* monitor: probes the 12
  wrk2-api endpoints (:36-49), POST for
  register/login/compose/upload/follow/unfollow with per-endpoint body
  synthesis (:104-134), connectivity pre-check before the monitoring loop
  (:82-96), JSONL record append (:297-298), summary/p95/p99 + per-endpoint
  reports (:318-397).
- ``monitor_http_responses.py`` — the *passive* fallback: GET-only sampling
  limited to the first 3 endpoints per cycle (:126-127), same record
  contract.
- ``collect_openapi_response.sh`` — the orchestrator: runs the monitor
  concurrently with collection (:84-89), optionally captures gateway traffic
  and post-processes it into ``traffic_analysis.json`` (:117-142, via
  tshark; here the captured :class:`~anomod.schemas.ApiBatch` is analyzed
  directly by :func:`anomod.io.api.analyze_api_batch` — same output, no
  pcap detour).

Requests execute against :class:`anomod.scenario.SyntheticGateway` (routing
by explicit SN owner service), so an active
:class:`~anomod.chaos.ChaosController` fault conditions monitor traffic the
same way it conditions every other modality.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod.scenario import RequestSpec, SyntheticGateway
from anomod.schemas import ApiBatch
from anomod.workload import sample_wrk2_request

# The 12 SN gateway endpoints (enhanced_openapi_monitor.py:36-49) with their
# owning services (docker-compose-gcov.yml service set) and the method rule
# of make_sample_request (POST iff register/login/compose/upload/
# follow/unfollow, :104).
SN_ENDPOINTS: Tuple[Tuple[str, str, str], ...] = (
    ("POST", "/wrk2-api/user/register", "user-service"),
    ("POST", "/wrk2-api/user/follow", "social-graph-service"),
    ("POST", "/wrk2-api/user/unfollow", "social-graph-service"),
    ("POST", "/wrk2-api/user/login", "user-service"),
    ("POST", "/wrk2-api/post/compose", "compose-post-service"),
    ("GET", "/wrk2-api/home-timeline/read", "home-timeline-service"),
    ("GET", "/wrk2-api/user-timeline/read", "user-timeline-service"),
    ("GET", "/wrk2-api/user/profile", "user-service"),
    ("POST", "/wrk2-api/media/upload", "media-service"),
    ("POST", "/wrk2-api/text/upload", "text-service"),
    ("GET", "/wrk2-api/url/shorten", "url-shorten-service"),
    ("POST", "/wrk2-api/user-mention/upload", "user-mention-service"),
)


def synthesize_body(path: str, seq: int) -> Optional[dict]:
    """Deterministic POST-body synthesis per endpoint kind
    (enhanced_openapi_monitor.py:104-134; time-derived uniqueness replaced
    by the monotone ``seq`` so runs are reproducible)."""
    if "register" in path:
        return {"first_name": "Test", "last_name": "User",
                "username": f"testuser_{seq}", "password": "testpass",
                "user_id": seq % 10_000}
    if "login" in path:
        return {"username": "testuser", "password": "testpass"}
    if "compose" in path:
        return {"username": "testuser", "user_id": 1, "text": "Test post",
                "media_ids": [], "media_types": [], "post_type": 0}
    if path.split("/")[-1] in ("upload", "follow", "unfollow"):
        return {}
    return None


def _form_encode(body: Optional[dict]) -> Optional[str]:
    """Flat ``k=v&k=v`` encoding of a synthesized probe body (the monitor
    sends form/JSON payloads; the gateway records the encoded length)."""
    if not body:
        return None
    return "&".join(f"{k}={v}" for k, v in body.items())


def _spec(method: str, path: str, owner: str,
          body: Optional[str] = None) -> RequestSpec:
    return RequestSpec(method, path, path, flow="monitor", owner=owner,
                       body=body)


# The three wrk2 mixed-workload templates (mixed-workload.lua:111-125),
# owner-resolved from the single SN_ENDPOINTS catalog so the two tables
# cannot drift.
_WRK2_TEMPLATES = ("/wrk2-api/post/compose", "/wrk2-api/home-timeline/read",
                   "/wrk2-api/user-timeline/read")
SN_OWNER_BY_TEMPLATE = {path: owner for _, path, owner in SN_ENDPOINTS
                        if path in _WRK2_TEMPLATES}


def run_wrk2_workload(gateway: SyntheticGateway, n_requests: int,
                      seed: int = 0,
                      rng: Optional[np.random.Generator] = None) -> List[int]:
    """Drive ``n_requests`` wrk2 mixed-workload requests (60/30/10 mix with
    the full compose content model, mixed-workload.lua:111-125) through the
    gateway.  Pass ``rng`` to continue one workload stream across several
    calls (the capture orchestrator drives a chunk between monitor cycles)."""
    if rng is None:
        rng = np.random.default_rng(seed)
    statuses: List[int] = []
    for _ in range(n_requests):
        req = sample_wrk2_request(rng)
        owner = SN_OWNER_BY_TEMPLATE[req.template]
        spec = RequestSpec(req.method, req.path, req.template,
                           flow="wrk2", owner=owner, body=req.body)
        statuses += gateway.execute([spec])
    return statuses


@dataclasses.dataclass
class MonitorReport:
    batch: ApiBatch
    connectivity: Dict[str, bool]
    n_cycles: int
    mode: str


class ActiveMonitor:
    """The enhanced monitor: every cycle probes all 12 endpoints with the
    method/body rules above.

    Intentional redesign vs the reference (enhanced_openapi_monitor.py):
    the reference samples only the first 5 *reachable* endpoints per cycle
    (:260,:279) and keeps its connectivity pre-check responses out of
    ``openapi_responses.jsonl``; this monitor probes all 12 endpoints every
    cycle regardless of connectivity and records the 12 pre-check probes in
    the batch.  Deterministic full coverage beats a reachability-dependent
    prefix for a synthetic SUT: the record count is exactly
    ``12 + cycles*12``, so artifacts are reproducible and fault-conditioned
    endpoint gaps can't silently shrink the sample.

    A second intentional deviation rides the gateway's record schema: the
    artifact ``content_length`` is the *request-body* length for POSTs that
    carry one (the synthesized wrk2/monitor body) and a synthetic
    *response* size otherwise, whereas the reference records the response
    Content-Length header for every exchange
    (enhanced_openapi_monitor.py:165).  Consumers of the api_responses
    artifact family should treat content_length as "dominant byte flow of
    the exchange", not strictly response size — chosen so the artifact's
    byte histogram reflects the wrk2 content model the corpus is built
    around (scenario.SyntheticGateway.execute)."""

    mode = "active"
    endpoints = SN_ENDPOINTS

    def __init__(self, seed: int = 0, controller=None) -> None:
        self._gw = SyntheticGateway(seed=seed, controller=controller)
        self._seq = 0

    def connectivity_check(self) -> Dict[str, bool]:
        """One GET per endpoint before monitoring
        (enhanced_openapi_monitor.py:82-96).  Against the synthetic SUT an
        endpoint is unreachable when its probe is *service-aborted* (503,
        the gateway's high-error fault response) — a sporadic baseline 500
        is an application error, not a connection failure, and the
        reference's pre-check only trips on connection errors."""
        out = {}
        for _, path, owner in self.endpoints:
            status = self._gw.execute([_spec("GET", path, owner)])[0]
            out[path] = status != 503
        return out

    def bodies(self) -> List[Optional[dict]]:
        """The POST bodies the next cycle would send (the reference's
        request-data synthesis, observable for tests/tools)."""
        out = []
        for method, path, _ in self.endpoints:
            out.append(synthesize_body(path, self._seq)
                       if method == "POST" else None)
            self._seq += 1
        return out

    def cycle(self) -> List[int]:
        bodies = self.bodies()    # advances the request-id sequence
        specs = [_spec(method, path, owner, body=_form_encode(body))
                 for (method, path, owner), body
                 in zip(self.endpoints, bodies)]
        return self._gw.execute(specs)

    def run(self, cycles: int = 10, before_cycle=None) -> MonitorReport:
        """Pre-check + probe cycles.  ``before_cycle(i)`` (when given) runs
        ahead of each cycle — the capture orchestrator uses it to land a
        chunk of wrk2 workload traffic on the shared gateway.  The
        connectivity pre-check always runs first (even for a workload-only
        cycles=0 capture) so the probe's RNG draws are position-stable."""
        connectivity = self.connectivity_check()
        if cycles == 0 and before_cycle is not None:
            before_cycle(0)
        for c in range(cycles):
            if before_cycle is not None:
                before_cycle(c)
            self.cycle()
        return MonitorReport(self._gw.to_api_batch(), connectivity,
                             cycles, self.mode)


class PassiveMonitor(ActiveMonitor):
    """The fallback sampler: GET-only, limited to the first 3 endpoints per
    cycle (monitor_http_responses.py:126-127)."""

    mode = "passive"

    def cycle(self) -> List[int]:
        specs = [_spec("GET", path, owner)
                 for _, path, owner in self.endpoints[:3]]
        return self._gw.execute(specs)


def capture_openapi_responses(out_dir: Optional[Path] = None,
                              mode: str = "active", cycles: int = 10,
                              seed: int = 0,
                              chaos: Optional[str] = None,
                              wrk2_requests: int = 0) -> MonitorReport:
    """Orchestrate a monitoring capture (collect_openapi_response.sh:60-143):
    optionally inject a fault, run the monitor (with ``wrk2_requests`` of
    concurrent mixed-workload traffic through the same gateway, the
    reference's monitor-plus-wrk2 arrangement), tear down (even on failure,
    like the reference's traps), and — when ``out_dir`` is given —
    materialize the full api_responses artifact family + collection report."""
    controller = None
    if chaos is not None:
        from anomod.chaos import ChaosController
        controller = ChaosController()
        controller.create(chaos)
    try:
        cls = ActiveMonitor if mode == "active" else PassiveMonitor
        monitor = cls(seed=seed, controller=controller)
        before_cycle = None
        if wrk2_requests:
            # interleave the workload with the probe cycles — the
            # reference's monitor-plus-wrk2 concurrency (collect_all_data.sh
            # :319-346) rendered as a deterministic round-robin: a chunk of
            # workload traffic lands on the shared gateway before every
            # monitor cycle, so artifact timestamps mix the two flows.
            wrk2_rng = np.random.default_rng(seed)
            n_cycles = max(cycles, 1)
            per = wrk2_requests // n_cycles
            extra = wrk2_requests - per * n_cycles

            def before_cycle(c):
                # remainder spread one-per-cycle (not lumped into cycle 0)
                # so small request counts still interleave with the probes
                run_wrk2_workload(monitor._gw,
                                  per + (1 if c < extra else 0),
                                  rng=wrk2_rng)
        report = monitor.run(cycles, before_cycle=before_cycle)
    finally:
        if controller is not None:
            controller.destroy_all()
    if out_dir is not None:
        from anomod.io.api import write_api_artifact_family
        out_dir = Path(out_dir)
        write_api_artifact_family(report.batch, out_dir)
        (out_dir / "collection_report.json").write_text(json.dumps({
            "mode": report.mode, "cycles": report.n_cycles,
            "chaos": chaos,
            "endpoints_monitored": [p for _, p, _ in SN_ENDPOINTS],
            "connectivity": report.connectivity,
            "total_requests": int(report.batch.n_records),
        }, indent=2))
    return report
