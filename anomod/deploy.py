"""Deployment planning — the analog of the reference's TT deploy scripts.

The reference deploys Train-Ticket with a three-step bash flow
(train-ticket/hack/deploy/{deploy.sh,utils.sh,gen-mysql-secret.sh}):

1. **Infrastructure** (utils.sh:30-46): helm-install the nacos MySQL cluster,
   nacos itself, and rabbitmq, each followed by a `kubectl rollout status`
   barrier.
2. **Databases** (utils.sh:59-88): either ONE shared `tsdb` MySQL release
   (default) or one release per service (`--independent-db`), then generate
   per-service DB secrets for the 27 `ts-*` services
   (gen-mysql-secret.sh:2,30-63) with `<SVC>_MYSQL_{HOST,PORT,DATABASE,USER,
   PASSWORD}` stringData keys.
3. **Services** (utils.sh:90-128): apply secrets + Services + Deployments —
   the SkyWalking variant when `--with-tracing` (plus the JaCoCo-injected
   manifest when present), then the skywalking stack; Prometheus/Grafana when
   `--with-monitoring` (deploy.sh:60-70).

Known reference quirks deliberately NOT replicated (SURVEY §5 quirks): the
`[ useOneHost == 0 ]` literal-string comparison in gen-mysql-secret.sh:58
makes the per-service-host branch unreachable — here shared vs per-service
hosts follow the *intended* semantics.

Everything is modeled as data: a :class:`DeployPlan` is an ordered tuple of
:class:`Action` (helm/kubectl argv + rollout barriers), renderable to a shell
script or executed against an in-process cluster model, so orchestration
logic is testable without helm or a cluster.  SN's analog is the compose
lifecycle (docker-compose -f docker-compose-gcov.yml down/up,
automated_multimodal_collection.sh:271-283) — modeled here too.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

# gen-mysql-secret.sh:2 — the 27 services (SURVEY §2.2 says 26; the list itself has 27) that get a DB secret
TT_DB_SERVICES: Tuple[str, ...] = (
    "assurance", "auth", "config", "consign-price", "consign", "contacts",
    "delivery", "food", "food-delivery", "inside-payment", "notification",
    "order-other", "order", "payment", "price", "route", "security",
    "station-food", "station", "ticket-office", "train-food", "train",
    "travel", "travel2", "user", "voucher", "wait-order",
)

# utils.sh:12-27 infra parameters
NACOS_DB = dict(release="nacosdb", user="nacos", password="Abcd1234#",
                database="nacos")
NACOS_RELEASE = "nacos"
RABBITMQ_RELEASE = "rabbitmq"
TS_DB = dict(user="ts", password="Ts_123456", database="ts")
_MYSQL_CHART = "deployment/kubernetes-manifests/quickstart-k8s/charts/mysql"
_NACOS_CHART = "deployment/kubernetes-manifests/quickstart-k8s/charts/nacos"
_RABBITMQ_CHART = "deployment/kubernetes-manifests/quickstart-k8s/charts/rabbitmq"


@dataclasses.dataclass(frozen=True)
class DeployFlags:
    """deploy.sh:70-95 argument surface."""
    all: bool = False
    independent_db: bool = False
    with_monitoring: bool = False
    with_tracing: bool = False

    @classmethod
    def parse(cls, args: Sequence[str]) -> "DeployFlags":
        known = {"--all": "all", "--independent-db": "independent_db",
                 "--with-monitoring": "with_monitoring",
                 "--with-tracing": "with_tracing"}
        vals = {}
        for a in args:
            key = known.get(a)
            if key is None:
                raise ValueError(f"unknown deploy arg: {a!r}")
            vals[key] = True
        return cls(**vals)


@dataclasses.dataclass(frozen=True)
class Action:
    """One step: an argv plus an optional readiness barrier."""
    kind: str                     # "helm" | "kubectl" | "compose" | "wait"
    argv: Tuple[str, ...]
    barrier: Optional[Tuple[str, ...]] = None   # rollout-status argv

    def render(self) -> str:
        lines = [" ".join(self.argv)]
        if self.barrier:
            lines.append(" ".join(self.barrier))
        return "\n".join(lines)


def _helm_mysql(release: str, user: str, password: str, database: str,
                namespace: str) -> Action:
    return Action("helm", (
        "helm", "install", release,
        "--set", f"mysql.mysqlUser={user}",
        "--set", f"mysql.mysqlPassword={password}",
        "--set", f"mysql.mysqlDatabase={database}",
        _MYSQL_CHART, "-n", namespace),
        barrier=("kubectl", "rollout", "status",
                 f"statefulset/{release}-mysql", "-n", namespace))


def mysql_secret_doc(service: str, host: str, user: str, password: str,
                     database: str) -> Dict:
    """One per-service Secret with the reference's env-prefix convention
    (gen-mysql-secret.sh:12-40: `<SVC>_MYSQL_` upper-snake keys)."""
    prefix = f"{service}-mysql-".replace("-", "_").upper()
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {"name": f"ts-{service}-mysql"},
        "type": "Opaque",
        "stringData": {
            f"{prefix}HOST": host,
            f"{prefix}PORT": "3306",
            f"{prefix}DATABASE": database,
            f"{prefix}USER": user,
            f"{prefix}PASSWORD": password,
        },
    }


def gen_mysql_secrets(shared_host: Optional[str] = None,
                      user: str = TS_DB["user"],
                      password: str = TS_DB["password"],
                      database: str = TS_DB["database"]) -> List[Dict]:
    """Secrets for all 27 DB-backed services.  ``shared_host`` set → the
    one-host layout (`tsdb-mysql-leader`); None → per-service hosts
    (`ts-<s>-mysql-leader`), the intended `--independent-db` semantics."""
    return [mysql_secret_doc(
        s, shared_host if shared_host else f"ts-{s}-mysql-leader",
        user, password, database) for s in TT_DB_SERVICES]


def tt_deploy_plan(flags: DeployFlags, namespace: str = "default",
                   with_jacoco: bool = True) -> List[Action]:
    """The full ordered action list deploy.sh would execute."""
    if flags.all:
        # deploy_all = per-service DBs + sw deploy + tracing + monitoring
        # (deploy.sh:27-35)
        flags = DeployFlags(independent_db=True, with_monitoring=True,
                            with_tracing=True)
    acts: List[Action] = []
    # step 1/3: infrastructure (utils.sh:30-46)
    acts.append(_helm_mysql(NACOS_DB["release"], NACOS_DB["user"],
                            NACOS_DB["password"], NACOS_DB["database"],
                            namespace))
    acts.append(Action("helm", (
        "helm", "install", NACOS_RELEASE,
        "--set", f"nacos.db.host={NACOS_DB['release']}-mysql-leader",
        "--set", f"nacos.db.username={NACOS_DB['user']}",
        "--set", f"nacos.db.name={NACOS_DB['database']}",
        "--set", f"nacos.db.password={NACOS_DB['password']}",
        _NACOS_CHART, "-n", namespace),
        barrier=("kubectl", "rollout", "status",
                 f"statefulset/{NACOS_RELEASE}", "-n", namespace)))
    acts.append(Action("helm", (
        "helm", "install", RABBITMQ_RELEASE, _RABBITMQ_CHART, "-n", namespace),
        barrier=("kubectl", "rollout", "status",
                 f"deployment/{RABBITMQ_RELEASE}", "-n", namespace)))
    # step 2/3: databases (utils.sh:59-88)
    if flags.independent_db:
        for s in TT_DB_SERVICES:
            acts.append(_helm_mysql(f"ts-{s}", TS_DB["user"],
                                    TS_DB["password"], TS_DB["database"],
                                    namespace))
    else:
        acts.append(_helm_mysql("tsdb", TS_DB["user"], TS_DB["password"],
                                TS_DB["database"], namespace))
    # step 3/3: secrets + services + deployments (utils.sh:90-128)
    acts.append(Action("kubectl", (
        "kubectl", "apply", "-f",
        "deployment/kubernetes-manifests/quickstart-k8s/yamls/secret.yaml",
        "-n", namespace)))
    acts.append(Action("kubectl", (
        "kubectl", "apply", "-f",
        "deployment/kubernetes-manifests/quickstart-k8s/yamls/svc.yaml",
        "-n", namespace)))
    if flags.with_tracing:
        acts.append(Action("kubectl", (
            "kubectl", "apply", "-f",
            "deployment/kubernetes-manifests/quickstart-k8s/yamls/sw_deploy.yaml",
            "-n", namespace)))
        if with_jacoco:
            acts.append(Action("kubectl", (
                "kubectl", "apply", "-f",
                "deployment/kubernetes-manifests/quickstart-k8s/yamls/"
                "sw_deploy.tcpserver.includes.yaml", "-n", namespace)))
        acts.append(Action("kubectl", (
            "kubectl", "apply", "-f",
            "deployment/kubernetes-manifests/skywalking", "-n", namespace)))
    else:
        acts.append(Action("kubectl", (
            "kubectl", "apply", "-f",
            "deployment/kubernetes-manifests/quickstart-k8s/yamls/deploy.yaml",
            "-n", namespace)))
    if flags.with_monitoring:
        acts.append(Action("kubectl", (
            "kubectl", "apply", "-f",
            "deployment/kubernetes-manifests/prometheus")))
    return acts


def sn_compose_plan(up: bool = True) -> List[Action]:
    """SN stack lifecycle (automated_multimodal_collection.sh:271-283)."""
    compose = ("docker-compose", "-f", "docker-compose-gcov.yml")
    if up:
        return [Action("compose", (*compose, "up", "-d"))]
    return [Action("compose", (*compose, "down", "--remove-orphans"))]


def render_plan(actions: Sequence[Action]) -> str:
    """The plan as the shell script the reference would have run."""
    return "\n".join(a.render() for a in actions) + "\n"


# ---------------------------------------------------------------------------
# Plan execution against the in-process cluster model
# ---------------------------------------------------------------------------

def execute_plan(actions: Sequence[Action], cluster=None) -> Dict[str, int]:
    """Apply a plan to a :class:`anomod.recovery.SyntheticCluster`-style
    world: helm releases and manifests register as deployed objects; each
    barrier advances the virtual clock past the rollout.  Returns the
    deployed-object census (by kind) for assertions."""
    census: Dict[str, int] = {"helm": 0, "kubectl": 0, "compose": 0,
                              "barriers": 0}
    for a in actions:
        census[a.kind] = census.get(a.kind, 0) + 1
        if a.barrier is not None:
            census["barriers"] += 1
            if cluster is not None:
                cluster.advance(30.0)     # rollout wait
    return census
