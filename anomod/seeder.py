"""SN social-graph seeder — deterministic graph synthesis + seeding program.

The reference seeds the SocialNetwork testbed from the ``socfb-Reed98``
Facebook edge list (962 users, ~18.8k undirected edges): register every user,
upload both follow directions per edge, optionally compose up to 20 posts per
user (average 10), all batched through an asyncio gate of 200 in-flight
requests with ``random.seed(1)`` determinism
(DeathStarBench/socialNetwork/scripts/init_social_graph.py:76-160).

The checkout does not materialize the dataset, so this module *synthesizes* a
graph with the same shape — a heavy-tailed Chung-Lu construction pinned to
the Reed98 scale — and compiles the same seeding program: batched
register/follow/compose request waves against the wrk2-api endpoints
(enhanced_openapi_monitor.py:36-49 vocabulary).  The resulting follower
counts also feed timeline-read weighting for SN traffic synthesis: hot users
dominate home-timeline reads the way the wrk2 Lua workload's zipfian user
draws do (mixed-workload.lua:33-83).
"""

from __future__ import annotations

from typing import Iterator, List, NamedTuple, Sequence, Tuple

import numpy as np

# socfb-Reed98 scale (init_social_graph.py:143-147 loads nodes+edges files)
REED98_USERS = 962
REED98_EDGES = 18_812

REGISTER = ("POST", "/wrk2-api/user/register")
FOLLOW = ("POST", "/wrk2-api/user/follow")
COMPOSE = ("POST", "/wrk2-api/post/compose")


class SocialGraph(NamedTuple):
    n_users: int
    edges: np.ndarray          # [E, 2] int32, undirected, deduped, u < v
    posts_per_user: np.ndarray  # [n_users] int32

    @property
    def n_edges(self) -> int:
        return int(self.edges.shape[0])

    def follower_counts(self) -> np.ndarray:
        """In-degree under both-direction follows (== undirected degree)."""
        deg = np.zeros(self.n_users, np.int64)
        np.add.at(deg, self.edges[:, 0], 1)
        np.add.at(deg, self.edges[:, 1], 1)
        return deg


def generate_graph(n_users: int = REED98_USERS,
                   n_edges: int = REED98_EDGES,
                   seed: int = 1,
                   tail: float = 1.8) -> SocialGraph:
    """Chung-Lu style heavy-tailed graph at the Reed98 scale.

    Vectorized: draw per-user weights from a Pareto tail, sample edge
    endpoints proportional to weight, drop self-loops/duplicates, and top up
    until the edge budget is met.  Deterministic in ``seed`` (the reference
    pins random.seed(1), init_social_graph.py:149).
    """
    feasible = n_users * (n_users - 1) // 2
    if n_edges > feasible:
        raise ValueError(
            f"n_edges={n_edges} exceeds the {feasible} unique pairs "
            f"available among {n_users} users")
    rng = np.random.default_rng(seed)
    w = rng.pareto(tail, n_users) + 1.0
    p = w / w.sum()
    seen = set()
    rows: List[Tuple[int, int]] = []
    # oversample in waves; heavy tail makes duplicates common
    stalled = 0
    while len(rows) < n_edges and stalled < 8:
        need = max(1024, int((n_edges - len(rows)) * 1.6))
        u = rng.choice(n_users, size=need, p=p)
        v = rng.choice(n_users, size=need, p=p)
        lo, hi = np.minimum(u, v), np.maximum(u, v)
        before = len(rows)
        for a, b in zip(lo.tolist(), hi.tolist()):
            if a == b or (a, b) in seen:
                continue
            seen.add((a, b))
            rows.append((a, b))
            if len(rows) == n_edges:
                break
        stalled = stalled + 1 if len(rows) == before else 0
    if len(rows) < n_edges:
        # near the feasibility ceiling weighted sampling stops landing on
        # unseen pairs — top up deterministically
        for a in range(n_users):
            for b in range(a + 1, n_users):
                if (a, b) not in seen:
                    seen.add((a, b))
                    rows.append((a, b))
                    if len(rows) == n_edges:
                        break
            if len(rows) == n_edges:
                break
    edges = np.array(rows, np.int32).reshape(-1, 2)
    # up to 20 posts per user, average 10 (init_social_graph.py:119)
    posts = rng.integers(0, 21, size=n_users).astype(np.int32)
    return SocialGraph(n_users, edges, posts)


class SeedOp(NamedTuple):
    method: str
    path: str
    params: Tuple[Tuple[str, str], ...]


def seeding_program(graph: SocialGraph, compose: bool = False) -> List[SeedOp]:
    """The full seeding request sequence: register every user, follow both
    directions per edge (init_social_graph.py:99-104 uploads edge[0]→edge[1]
    AND edge[1]→edge[0]), optionally compose posts."""
    ops: List[SeedOp] = []
    for i in range(graph.n_users):
        ops.append(SeedOp(*REGISTER, (
            ("first_name", f"first_name_{i}"), ("last_name", f"last_name_{i}"),
            ("username", f"username_{i}"), ("password", f"password_{i}"),
            ("user_id", str(i)))))
    for a, b in graph.edges.tolist():
        ops.append(SeedOp(*FOLLOW, (("user_name", f"username_{a}"),
                                    ("followee_name", f"username_{b}"))))
        ops.append(SeedOp(*FOLLOW, (("user_name", f"username_{b}"),
                                    ("followee_name", f"username_{a}"))))
    if compose:
        for i in range(graph.n_users):
            for _ in range(int(graph.posts_per_user[i])):
                ops.append(SeedOp(*COMPOSE, (("username", f"username_{i}"),
                                             ("user_id", str(i)))))
    return ops


def waves(ops: Sequence[SeedOp], limit: int = 200) -> Iterator[Sequence[SeedOp]]:
    """Batch the program into concurrent waves of ``limit`` in-flight requests
    (the asyncio connector gate, init_social_graph.py:78,156)."""
    for i in range(0, len(ops), limit):
        yield ops[i:i + limit]


def timeline_weights(graph: SocialGraph) -> np.ndarray:
    """Per-user home-timeline read propensity ∝ follower count (hot users are
    read more) — feeds SN traffic synthesis."""
    deg = graph.follower_counts().astype(np.float64)
    total = deg.sum()
    if total == 0:  # edgeless graph: uniform reads
        return np.full(graph.n_users, 1.0 / max(graph.n_users, 1))
    return deg / total
