"""Windowed RCA node/edge features — ONE definition, shared online and
offline.

The offline train/eval harness (anomod.rca) and the online serve-tick
RCA plane (anomod.serve.rca) must score the SAME feature space: a
culprit ranking computed live against features that drifted from the
training features is silently a different model.  Everything here is
pure numpy over a SpanBatch + ReplayConfig — no jax, no global state —
so the offline batch builder and the online single-graph extractor call
literally the same functions and a bit-exact parity test
(tests/test_rca_features.py) pins that they can never drift.

Contents (moved verbatim out of ``anomod/rca.py``):

- :func:`agg_feature_block` — [S, W, 4] windowed aggregates (count,
  err rate, mean log-latency, 5xx rate) via the replay plane.
- :func:`windowed_features` — node features, optionally doubled with the
  per-service OUT-EDGE block (the link-fault evidence channel).
- :func:`edge_feature_block` — [E, W, 4] per-call-graph-edge aggregates
  (the line-graph model's token features).
- :func:`pad_edge_arrays` — the fixed-shape edge padding the offline
  dataset builder and any fixed-shape consumer share.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from anomod.replay import ReplayConfig, replay_numpy, stage_columns


def agg_feature_block(batch, services, cfg: ReplayConfig,
                      t0_us=None) -> np.ndarray:
    """[S, W, 4]: count, err_rate, mean log-latency, 5xx rate per window."""
    chunks, _ = stage_columns(batch, cfg, t0_us=t0_us)
    st = replay_numpy(chunks, cfg)
    from anomod.replay import F_ERR, F_LOGLAT, F_STATUS5XX
    agg = st.agg.reshape(len(services), cfg.n_windows, -1)
    count = agg[..., 0]
    safe = np.maximum(count, 1.0)
    return np.stack([
        np.log1p(count), agg[..., F_ERR] / safe, agg[..., F_LOGLAT] / safe,
        agg[..., F_STATUS5XX] / safe,
    ], axis=-1).astype(np.float32)


def windowed_features(batch, services, cfg: ReplayConfig,
                      edge_features: bool = False) -> np.ndarray:
    """[S, W, 4] node features — or [S, W, 8] with ``edge_features``: the
    same four aggregates computed a second time over each service's
    OUT-EDGE spans (spans whose parent belongs to that service, i.e. the
    callee side of its outgoing calls).  The out-edge block is the
    offline counterpart of the streaming detector's caller-keyed
    out-edge plane: a link fault (synth fault_locus="edge") is invisible
    in every node aggregate but lands exactly in the culprit's out-edge
    block — without it the models have no evidence channel for edge
    faults at all (see docs/BENCHMARKS.md, generator-leak retraction)."""
    svc_index = {s: i for i, s in enumerate(services)}
    remap = np.array([svc_index.get(s, 0) for s in batch.services] or [0],
                     np.int32)
    batch = batch._replace(service=remap[batch.service],
                           services=tuple(services))
    # one time origin for BOTH blocks: the edge subset excludes root
    # spans, so letting stage_columns re-derive t0 from it would slide
    # the edge block's window grid relative to the node block's
    t0_us = int(batch.start_us.min()) if batch.n_spans else 0
    node = agg_feature_block(batch, services, cfg, t0_us=t0_us)
    if not edge_features:
        return node
    from anomod.schemas import take_spans
    psvc = np.full(batch.n_spans, -1, np.int32)
    has = batch.parent >= 0
    psvc[has] = batch.service[batch.parent[has]]
    cross = (psvc >= 0) & (psvc != batch.service)
    if not cross.any():
        return np.concatenate([node, np.zeros_like(node)], axis=-1)
    edge_batch = take_spans(batch, cross)._replace(service=psvc[cross])
    edge = agg_feature_block(edge_batch, services, cfg, t0_us=t0_us)
    return np.concatenate([node, edge], axis=-1)


def edge_feature_block(batch, services, g, cfg: ReplayConfig) -> np.ndarray:
    """[E, W, 4] windowed aggregates PER call-graph edge of ``g`` —
    count/err/log-lat/5xx of the spans riding each (caller, callee) edge
    (child spans keyed by their parent's service, the
    anomod.replay.edge_keyed_batch convention).  The line-graph model's
    token features: a link fault lands in exactly one row here, where the
    per-caller out-edge BLOCK (windowed_features) sums it with every
    other callee of the same caller."""
    svc_index = {s: i for i, s in enumerate(services)}
    remap = np.array([svc_index.get(s, 0) for s in batch.services] or [0],
                     np.int32)
    svc = remap[batch.service]
    psvc = np.full(batch.n_spans, -1, np.int32)
    has = batch.parent >= 0
    psvc[has] = svc[batch.parent[has]]
    S = len(services)
    eid_of_pair = {int(a) * S + int(b): i
                   for i, (a, b) in enumerate(zip(g.edge_src, g.edge_dst))}
    E = len(eid_of_pair)
    pair = psvc.astype(np.int64) * S + svc
    eid = np.array([eid_of_pair.get(int(p), -1) for p in pair], np.int32)
    keep = (psvc >= 0) & (eid >= 0)
    if not keep.any() or E == 0:
        return np.zeros((E, cfg.n_windows, 4), np.float32)
    from anomod.schemas import take_spans
    eb = take_spans(batch, keep)._replace(
        service=eid[keep],
        services=tuple(f"e{i}" for i in range(E)))
    cfg_e = dataclasses.replace(cfg, n_services=E)
    t0_us = int(batch.start_us.min()) if batch.n_spans else 0
    return agg_feature_block(eb, eb.services, cfg_e, t0_us=t0_us)


def pad_edge_arrays(g, e_max: int) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray]:
    """(edge_src, edge_dst, edge_mask) of one ServiceGraph padded to the
    fixed ``e_max`` shape — the ONE edge-padding definition shared by
    the offline dataset builder and fixed-shape online consumers."""
    if g.n_edges > e_max:
        raise ValueError(f"graph has {g.n_edges} edges > e_max={e_max}")
    src = np.zeros(e_max, np.int32)
    dst = np.zeros(e_max, np.int32)
    mask = np.zeros(e_max, np.bool_)
    src[:g.n_edges] = g.edge_src
    dst[:g.n_edges] = g.edge_dst
    mask[:g.n_edges] = True
    return src, dst, mask
