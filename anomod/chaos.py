"""Fault-injection subsystem — the framework analog of the reference chaos layer.

The reference injects faults two ways:

- **Chaos Mesh CRDs** for TT performance/service/database faults
  (chaos-experiments/*.yaml applied by start_chaos.sh:41, removed by
  stop_chaos.sh + the campaign-level sweep run_all_experiments.sh:169-217).
- **ChaosBlade CLI** for every SN fault (host-level cpu/network/disk, process
  kill, redis cache-limit — automated_multimodal_collection.sh:323-497) and
  for the TT code-level JVM faults (`blade create k8s container-jvm
  return/throwCustomException`, run_experiment.sh:293-351).  SN code-level
  faults are plain ``docker stop`` (automated_multimodal_collection.sh:464-479).

This module models all three dispatch planes as data: each
:class:`~anomod.labels.FaultLabel` renders to the CRD document / blade argv /
docker argv the reference would have issued, parses back (CRD metadata labels
carry anomaly_level/anomaly_type/target_service — Lv_P_CPU_preserve.yaml:6-11),
and an in-process :class:`ChaosController` owns the inject→status→destroy
lifecycle (UID extraction semantics of run_experiment.sh:357-372, pre-run
sweep semantics of cleanup_all_previous_anomalies,
automated_multimodal_collection.sh:732-781) against the synthetic SUT: active
faults condition the generator via the same (latency×, error-rate) effect
model the corpus is built from.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Dict, List, Optional, Sequence, Tuple

from anomod.labels import FaultLabel, label_for

# ---------------------------------------------------------------------------
# Chaos Mesh CRDs (TT chaosmesh faults)
# ---------------------------------------------------------------------------

_API_VERSION = "chaos-mesh.org/v1alpha1"
_CRD_NAMESPACE = "chaos-mesh"
_TARGET_NAMESPACE = "default"

# Per-experiment CRD shape parameters, matching the reference definitions
# (chaos-experiments/<experiment>.yaml) semantically: kind, chaos action and
# its tuning knobs.  The selector always pins ``app: <pod app label>``.
_MESH_SHAPES: Dict[str, Dict] = {
    # StressChaos, 2 workers × 80% load (Lv_P_CPU_preserve.yaml:18-22)
    "Lv_P_CPU_preserve": dict(
        kind="StressChaos", name="preserve-cpu-contention",
        app="ts-preserve-service", mode="fixed-percent", value="100",
        spec={"stressors": {"cpu": {"workers": 2, "load": 80}}}),
    # StressChaos via stress-ng iomix (Lv_P_DISKIO_preserve.yaml:19)
    "Lv_P_DISKIO_preserve": dict(
        kind="StressChaos", name="preserve-disk-io-stress",
        app="ts-preserve-service", mode="fixed-percent", value="100",
        spec={"stressngStressors": "--iomix 2 --iomix-bytes 1G --timeout 0"}),
    # NetworkChaos 90% loss (Lv_P_NETLOSS_preserve.yaml:17-20)
    "Lv_P_NETLOSS_preserve": dict(
        kind="NetworkChaos", name="preserve-network-loss",
        app="ts-preserve-service", mode="fixed-percent", value="100",
        spec={"action": "loss", "loss": {"loss": "90", "correlation": "0"}}),
    # DNSChaos: order-service names fail to resolve
    # (Lv_S_DNSFAIL_preserve_no_order.yaml:12-20)
    "Lv_S_DNSFAIL_preserve_no_order": dict(
        kind="DNSChaos", name="preserve-dns-no-order",
        app="ts-preserve-service", mode="one",
        spec={"action": "error",
              "patterns": ["ts-order-service*", "ts-order-other-service*"]}),
    # HTTPChaos 70% abort → 503 on the preserve API
    # (Lv_S_HTTPABORT_preserve.yaml:13-24)
    "Lv_S_HTTPABORT_preserve": dict(
        kind="HTTPChaos", name="preserve-http-abort",
        app="ts-preserve-service", mode="fixed-percent", value="70",
        spec={"target": "Request", "port": 14568, "method": "POST",
              "path": "/api/v1/preserveservice/*", "abort": True,
              "replace": {"code": 503}}),
    # Schedule wrapping PodChaos pod-kill every 3 s
    # (Lv_S_KILLPOD_preserve.yaml:15-22)
    "Lv_S_KILLPOD_preserve": dict(
        kind="Schedule", name="preserve-kill-scheduled",
        app="ts-preserve-service", mode=None,
        spec={"schedule": "@every 3s", "type": "PodChaos",
              "podChaos": {
                  "action": "pod-kill", "mode": "one",
                  "selector": {"namespaces": [_TARGET_NAMESPACE],
                               "labelSelectors": {"app": "ts-preserve-service"}}}}),
    # StressChaos memory 85% on the shared MySQL (Lv_D_cachelimit.yaml:17-21)
    "Lv_D_cachelimit": dict(
        kind="StressChaos", name="db-cache-limit",
        app="tsdb-mysql", mode="fixed-percent", value="100",
        spec={"stressors": {"memory": {"workers": 1, "size": "85%"}}}),
    # NetworkChaos 8s±2s delay app→MySQL
    # (Lv_D_CONNECTION_POOL_exhaustion.yaml:17-32)
    "Lv_D_CONNECTION_POOL_exhaustion": dict(
        kind="NetworkChaos", name="db-connection-pool-exhaustion",
        app="tsdb-mysql", mode="all",
        spec={"action": "delay",
              "delay": {"latency": "8s", "jitter": "2s", "correlation": "0"},
              "direction": "from",
              "target": {"mode": "all", "selector": {
                  "namespaces": [_TARGET_NAMESPACE],
                  "expressionSelectors": [{
                      "key": "app", "operator": "In",
                      "values": ["ts-order-service", "ts-preserve-service",
                                 "ts-user-service"]}]}}}),
    # NetworkChaos 15s±5s delay MySQL→app (Lv_D_TRANSACTION_timeout.yaml:17-31)
    "Lv_D_TRANSACTION_timeout": dict(
        kind="NetworkChaos", name="db-transaction-timeout",
        app="tsdb-mysql", mode="all",
        spec={"action": "delay",
              "delay": {"latency": "15s", "jitter": "5s", "correlation": "0"},
              "direction": "to",
              "target": {"mode": "all", "selector": {
                  "namespaces": [_TARGET_NAMESPACE],
                  "expressionSelectors": [{
                      "key": "app", "operator": "In",
                      "values": ["ts-order-service", "ts-preserve-service",
                                 "ts-travel-service", "ts-user-service"]}]}}}),
}


def build_mesh_crd(label_or_name) -> Dict:
    """Render the Chaos Mesh CRD document for a TT chaosmesh experiment."""
    label = _as_label(label_or_name)
    shape = _MESH_SHAPES.get(label.experiment)
    if shape is None:
        raise ValueError(f"{label.experiment} is not a Chaos Mesh experiment")
    doc: Dict = {
        "apiVersion": _API_VERSION,
        "kind": shape["kind"],
        "metadata": {
            "name": shape["name"],
            "namespace": _CRD_NAMESPACE,
            "labels": {
                "experiment_id": f"chaos-{shape['name']}",
                "anomaly_level": label.anomaly_level,
                "anomaly_type": label.anomaly_type,
                "target_service": shape["app"],
            },
        },
        "spec": dict(shape["spec"]),
    }
    if shape["kind"] != "Schedule":  # Schedule nests the selector in podChaos
        doc["spec"]["selector"] = {
            "namespaces": [_TARGET_NAMESPACE],
            "labelSelectors": {"app": shape["app"]},
        }
        if shape["mode"] is not None:
            doc["spec"]["mode"] = shape["mode"]
        if shape.get("value") is not None:
            doc["spec"]["value"] = shape["value"]
    return doc


def parse_mesh_crd(doc: Dict) -> Optional[FaultLabel]:
    """Recover the FaultLabel from a CRD's metadata labels.

    Mirrors how start_chaos.sh:24-27 reads experiment metadata back out of the
    YAML.  Matching is by (anomaly_level, anomaly_type) + CRD name against the
    known taxonomy; returns None for unknown documents.
    """
    meta = doc.get("metadata", {}).get("labels", {})
    lvl, typ = meta.get("anomaly_level"), meta.get("anomaly_type")
    name = doc.get("metadata", {}).get("name")
    for exp, shape in _MESH_SHAPES.items():
        label = label_for(exp)
        if shape["name"] == name or (
                label and label.anomaly_level == lvl and label.anomaly_type == typ):
            return label
    return None


def mesh_crd_yaml(label_or_name) -> str:
    """CRD as YAML text (what `kubectl apply -f` would consume)."""
    import yaml
    return yaml.safe_dump(build_mesh_crd(label_or_name), sort_keys=False)


def parse_mesh_crd_yaml(text: str) -> Optional[FaultLabel]:
    import yaml
    return parse_mesh_crd(yaml.safe_load(text))


# ---------------------------------------------------------------------------
# ChaosBlade argv (SN host faults + TT JVM faults) and docker argv
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BladeCommand:
    """One `blade create ...` invocation (argv after the binary)."""
    args: Tuple[str, ...]
    needs_sudo: bool = False          # automated_multimodal_collection.sh:347,377
    k8s: bool = False                 # TT container-jvm faults

    @property
    def action(self) -> str:
        return " ".join(self.args[1:3] if not self.k8s else self.args[1:4])


# SN process-kill targets: service → C++ process name
# (automated_multimodal_collection.sh:376,391,402).
_SN_PROCESS = {
    "user-timeline-service": "UserTimelineService",
    "media-service": "MediaService",
    "social-graph-service": "SocialGraphService",
}
# SN redis cache-limit targets: culprit service → redis compose container
# (automated_multimodal_collection.sh:416-418).
_SN_REDIS = {
    "home-timeline-service": "socialnetwork_home-timeline-redis_1",
    "user-timeline-service": "socialnetwork_user-timeline-redis_1",
    "social-graph-service": "socialnetwork_social-graph-redis_1",
}
# TT JVM fault plans: experiment → (blade jvm action, class, method, extras)
# (run_experiment.sh:299-346).
_TT_JVM = {
    "Lv_C_security_check": (
        "return", "security.service.SecurityServiceImpl", "check",
        ("--value",
         "new edu.fudan.common.util.Response(0, 'CHAOS_SECURITY_CHECK_FAILURE', null)")),
    "Lv_C_exception_injection": (
        "throwCustomException", "order.service.OrderServiceImpl", "create",
        ("--exception", "java.lang.RuntimeException",
         "--exception-message", "CHAOS_EXCEPTION_INJECTION")),
    "Lv_C_travel_detail_failure": (
        "return", "travel.service.TravelServiceImpl", "getTripAllDetailInfo",
        ("--value", "null")),
}


def blade_create_command(label_or_name) -> Optional[BladeCommand]:
    """The `blade create` argv for a chaosblade experiment; None when the
    fault is not blade-driven (Chaos Mesh, docker stop, or normal)."""
    label = _as_label(label_or_name)
    if label.chaos_tool != "chaosblade":
        return None
    exp, typ, tgt = label.experiment, label.anomaly_type, label.target_service
    if label.testbed == "TT":
        action, cls, method, extras = _TT_JVM[exp]
        pod = f"{tgt}-0"  # synthetic pod name; live path resolves via kubectl
        return BladeCommand(
            ("create", "k8s", "container-jvm", action,
             "--classname", cls, "--methodname", method, *extras,
             "--names", pod, "--container-names", tgt,
             "--process", "java", "--namespace", _TARGET_NAMESPACE),
            needs_sudo=False, k8s=True)
    if typ == "cpu_contention":
        return BladeCommand(("create", "cpu", "load",
                             "--cpu-percent", "100", "--timeout", "300"))
    if typ == "network_loss":
        return BladeCommand(("create", "network", "loss", "--interface",
                             "docker0", "--percent", "50", "--timeout", "300"),
                            needs_sudo=True)
    if typ == "disk_io_stress":
        return BladeCommand(("create", "disk", "burn", "--read", "--write",
                             "--path", "/var/log", "--size", "1024",
                             "--timeout", "300"))
    if typ == "kill_service_instance":
        return BladeCommand(("create", "process", "kill", "--process",
                             _SN_PROCESS[tgt], "--signal", "9"),
                            needs_sudo=True)
    if typ == "cache_limit":
        return BladeCommand(("create", "redis", "cache-limit", "--addr",
                             f"{_SN_REDIS[tgt]}:6379", "--password", "",
                             "--percent", "50", "--timeout", "300"))
    if typ == "process_stop":
        return None  # docker stop, not blade — see docker_command
    raise ValueError(f"no blade plan for {exp}")


def docker_command(label_or_name) -> Optional[Tuple[str, ...]]:
    """SN code-level faults are plain container stops
    (automated_multimodal_collection.sh:464-479)."""
    label = _as_label(label_or_name)
    if label.testbed == "SN" and label.anomaly_type == "process_stop":
        return ("docker", "stop", f"socialnetwork_{label.target_service}_1")
    return None


# UID extraction, the three observed ChaosBlade output formats
# (run_experiment.sh:357-368).
_UID_RESULT = re.compile(r'"result"\s*:\s*"([^"]+)"')
_UID_UID = re.compile(r'"Uid"\s*:\s*"([^"]+)"')
_UID_TEXT = re.compile(r"uid\s*:\s*(\S+)")


def parse_blade_output(output: str) -> Optional[str]:
    """Extract the experiment UID from `blade create` output (JSON
    ``result``/``Uid`` fields, or legacy ``uid: <x>`` text), else None."""
    for pat in (_UID_RESULT, _UID_UID, _UID_TEXT):
        m = pat.search(output)
        if m:
            return m.group(1)
    return None


# ---------------------------------------------------------------------------
# In-process controller (lifecycle over the synthetic SUT)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ChaosHandle:
    uid: str
    label: FaultLabel
    plan: str          # "mesh" | "blade" | "docker" | "none"


class ChaosController:
    """Owns inject→status→destroy for synthetic experiments.

    Lifecycle semantics follow the reference: `create` returns a UID
    (blade-style JSON), `status` lists active experiments (`blade status
    --type create`), `destroy`/`destroy_all` tear down (stop_chaos.sh; the
    pre-run sweep of automated_multimodal_collection.sh:732-781 destroys
    *everything* left over).  Active faults expose the generator's effect
    model so collection conditioned through a controller matches collection
    conditioned directly by label.
    """

    def __init__(self) -> None:
        self._active: Dict[str, ChaosHandle] = {}
        self._counter = 0

    def _new_uid(self, label: FaultLabel) -> str:
        self._counter += 1
        h = hashlib.sha1(f"{label.experiment}:{self._counter}".encode())
        return h.hexdigest()[:16]

    def create(self, label_or_name) -> ChaosHandle:
        label = _as_label(label_or_name)
        if not label.is_anomaly:
            plan = "none"
        elif label.chaos_tool == "chaosmesh":
            build_mesh_crd(label)          # validates a CRD shape exists
            plan = "mesh"
        elif docker_command(label) is not None:
            plan = "docker"
        else:
            cmd = blade_create_command(label)
            if cmd is None:
                raise ValueError(f"no injection plan for {label.experiment}")
            plan = "blade"
        handle = ChaosHandle(self._new_uid(label), label, plan)
        if label.is_anomaly:
            self._active[handle.uid] = handle
        return handle

    def create_result_json(self, label_or_name) -> str:
        """Blade-shaped create output (what parse_blade_output consumes)."""
        h = self.create(label_or_name)
        return json.dumps({"code": 200, "success": True, "result": h.uid})

    def status(self) -> List[ChaosHandle]:
        return list(self._active.values())

    def destroy(self, uid: str) -> bool:
        return self._active.pop(uid, None) is not None

    def destroy_all(self) -> int:
        n = len(self._active)
        self._active.clear()
        return n

    def active_effects(self, service: str) -> Tuple[float, float]:
        """Aggregate (latency_multiplier, error_probability) the active
        faults impose on ``service`` — the synthetic SUT's response to
        injection.  Multiple faults compound multiplicatively on latency and
        take the max error rate, floored at the generator's baseline."""
        from anomod.synth import _fault_effects
        lat, err = 1.0, 0.002
        for h in self._active.values():
            f_lat, f_err = _fault_effects(h.label)
            tgt = h.label.target_service
            if tgt == service or tgt == "":   # host-level faults hit everyone
                lat *= f_lat
                err = max(err, f_err)
        return lat, err

    # Context-manager form: the reference guards every run with ERR/EXIT
    # traps that destroy chaos on the way out (run_experiment.sh:407-411,
    # run_all_experiments.sh:12-30).
    def inject(self, label_or_name) -> "_Injection":
        return _Injection(self, _as_label(label_or_name))


class _Injection:
    def __init__(self, ctl: ChaosController, label: FaultLabel) -> None:
        self._ctl, self._label = ctl, label
        self.handle: Optional[ChaosHandle] = None

    def __enter__(self) -> ChaosHandle:
        self.handle = self._ctl.create(self._label)
        return self.handle

    def __exit__(self, *exc) -> None:
        if self.handle is not None:
            self._ctl.destroy(self.handle.uid)


def _as_label(label_or_name) -> FaultLabel:
    if isinstance(label_or_name, FaultLabel):
        return label_or_name
    label = label_for(str(label_or_name))
    if label is None:
        raise ValueError(f"unknown experiment: {label_or_name!r}")
    return label


def mesh_experiments() -> List[str]:
    return sorted(_MESH_SHAPES)
