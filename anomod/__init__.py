"""anomod — TPU-native anomaly-detection & root-cause-analysis framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the AnoMod
reference dataset + toolchain (EvoTestOps/AnoMod): typed loaders for the five
synchronized modalities (logs, metrics, traces, API responses, code coverage)
of the SocialNetwork (SN) and Train-Ticket (TT) testbeds, the chaos fault
taxonomy, service-dependency-graph construction from spans, streaming-sketch
featurization (t-digest / HyperLogLog), anomaly detection and GNN root-cause
localization — with a ``backend={cpu, jax-tpu}`` switch and pod-sharded replay.

Reference behavior contracts are cited per-module as
``/root/reference/<path>:<line>``.
"""

__version__ = "0.1.0"

from anomod import config as config
from anomod import schemas as schemas
from anomod import labels as labels

__all__ = ["config", "schemas", "labels", "__version__"]
