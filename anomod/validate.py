"""Data-quality validation — the reference's embedded collector checks.

The reference validates as it collects: non-empty log check + retry
(collect_log.sh:91-99,154-165), empty-Prometheus-query warnings
(fetch_prometheus_metrics.py:40-42), trace dedup by traceID
(collect_trace.sh:52-58; trace_collector.py:358-360), endpoint connectivity
pre-checks (enhanced_openapi_monitor.py:82-96), and exec-file presence
summaries (collect_coverage_reports.sh:176-191).  This module applies the
same checks to loaded Experiment bundles and emits a JSON-able collection
report in the spirit of log_collector.py:179-200.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from anomod.schemas import Experiment, LOG_ERROR, SpanBatch


@dataclasses.dataclass
class ValidationIssue:
    severity: str        # "warn" | "error"
    modality: str
    message: str


@dataclasses.dataclass
class ValidationReport:
    experiment: str
    testbed: str
    synthetic: bool
    counts: Dict[str, int]
    issues: List[ValidationIssue]

    @property
    def ok(self) -> bool:
        return not any(i.severity == "error" for i in self.issues)

    def to_dict(self) -> dict:
        return {
            "experiment": self.experiment, "testbed": self.testbed,
            "synthetic": self.synthetic, "ok": self.ok, "counts": self.counts,
            "issues": [dataclasses.asdict(i) for i in self.issues],
        }


def dedup_traces(batch: SpanBatch) -> SpanBatch:
    """Drop exact duplicate spans from re-paginated collections: the columnar
    analog of the reference's jq/set() traceID dedup.  A duplicate is a row
    whose (trace, service, endpoint, start, duration) quintuple repeats."""
    if batch.n_spans == 0:
        return batch
    key = np.stack([batch.trace.astype(np.int64), batch.service.astype(np.int64),
                    batch.endpoint.astype(np.int64), batch.start_us,
                    batch.duration_us], axis=1)
    _, first_idx = np.unique(key, axis=0, return_index=True)
    if first_idx.shape[0] == batch.n_spans:
        return batch
    keep = np.sort(first_idx)
    remap = np.full(batch.n_spans, -1, np.int32)
    remap[keep] = np.arange(keep.shape[0], dtype=np.int32)
    parent = batch.parent[keep]
    parent = np.where(parent >= 0, remap[np.clip(parent, 0, None)], -1)
    return batch._replace(
        trace=batch.trace[keep], parent=parent.astype(np.int32),
        service=batch.service[keep], endpoint=batch.endpoint[keep],
        start_us=batch.start_us[keep], duration_us=batch.duration_us[keep],
        is_error=batch.is_error[keep], status=batch.status[keep],
        kind=batch.kind[keep])


def validate_experiment(exp: Experiment) -> ValidationReport:
    issues: List[ValidationIssue] = []
    counts: Dict[str, int] = {}

    def warn(mod, msg):
        issues.append(ValidationIssue("warn", mod, msg))

    def error(mod, msg):
        issues.append(ValidationIssue("error", mod, msg))

    # traces
    if exp.spans is None or exp.spans.n_spans == 0:
        error("traces", "no spans collected")
        counts["spans"] = 0
    else:
        counts["spans"] = exp.spans.n_spans
        counts["traces"] = exp.spans.n_traces
        deduped = dedup_traces(exp.spans)
        if deduped.n_spans < exp.spans.n_spans:
            warn("traces", f"{exp.spans.n_spans - deduped.n_spans} duplicate "
                 "spans (re-paginated collection?)")
        orphan = ((exp.spans.parent < -1)
                  | (exp.spans.parent >= exp.spans.n_spans)).sum()
        if orphan:
            error("traces", f"{orphan} out-of-range parent references")
        if (exp.spans.duration_us < 0).any():
            error("traces", "negative span durations")
        # parent-resolution rate: the call-graph, edge-attribution, and
        # per-edge featurization planes all key spans by caller — a
        # collection whose parentSpanId join mostly failed silently
        # degrades every edge view to node evidence
        resolved = float((exp.spans.parent >= 0).mean())
        counts["parent_resolution_rate"] = round(resolved, 4)
        if resolved < 0.5:
            warn("traces", f"only {resolved:.0%} of spans have a resolved "
                 "parent — edge-keyed planes (stream edge attribution, "
                 "per-edge percentiles) degrade toward node evidence")

    # metrics
    if exp.metrics is None or exp.metrics.n_samples == 0:
        error("metrics", "no metric samples")
        counts["metric_samples"] = 0
    else:
        counts["metric_samples"] = exp.metrics.n_samples
        nan_frac = float(np.isnan(exp.metrics.value).mean())
        if nan_frac > 0.2:
            warn("metrics", f"{nan_frac:.0%} NaN samples")
        empty = [m for i, m in enumerate(exp.metrics.metric_names)
                 if not (exp.metrics.metric == i).any()]
        for m in empty:
            warn("metrics", f"query '{m}' returned no data")  # fetcher :40-42

    # logs — the reference's empty-log + "only tracing statements" checks
    if exp.logs is None or exp.logs.n_lines == 0:
        warn("logs", "no log lines")
        counts["log_lines"] = 0
    else:
        counts["log_lines"] = exp.logs.n_lines
        per_svc = np.bincount(exp.logs.service,
                              minlength=len(exp.logs.services))
        for i, svc in enumerate(exp.logs.services):
            if per_svc[i] == 0:
                warn("logs", f"{svc}: log file not generated")

    # api
    if exp.api is None or exp.api.n_records == 0:
        warn("api", "no API response records")
        counts["api_records"] = 0
    else:
        counts["api_records"] = exp.api.n_records
        reachable = int((exp.api.status > 0).sum())
        if reachable == 0:
            error("api", "no endpoint reachable (connectivity pre-check)")

    # coverage — exec/report presence summary
    if exp.coverage is None or len(exp.coverage.paths) == 0:
        warn("coverage", "no coverage artifacts")
        counts["coverage_files"] = 0
    else:
        counts["coverage_files"] = len(exp.coverage.paths)
        if int(exp.coverage.lines_total.sum()) == 0:
            error("coverage", "coverage artifacts have zero executable lines")

    return ValidationReport(experiment=exp.name, testbed=exp.testbed,
                            synthetic=exp.synthetic, counts=counts,
                            issues=issues)


def corpus_summary(testbed: str, reports: List[ValidationReport],
                   cache_stats: Optional[dict] = None) -> dict:
    """The corpus-level validation document the CLI emits.

    ``cache_stats`` (anomod.io.cache hit/miss/store/error counters for the
    load that produced the corpus) rides along when the corpus came from
    the archived tree — an all-miss load on a supposedly warm cache is
    itself a data-pipeline quality signal worth surfacing."""
    out = {
        "testbed": testbed,
        "ok": all(r.ok for r in reports),
        "reports": [r.to_dict() for r in reports],
    }
    if cache_stats is not None:
        out["ingest_cache"] = dict(cache_stats)
    return out
