"""API-response JSONL loader → ApiBatch.

Record contract (enhanced_openapi_monitor.py:155-169): one JSON object per
line with ``timestamp`` (ISO), ``endpoint``, ``method``, ``status_code``,
``latency_ms``, ``content_length``, ...  SN layout:
``<exp>/openapi_responses.jsonl``; TT layout: ``<exp>/<YYYYMMDD>/api_responses.jsonl``.
"""

from __future__ import annotations

import json
from datetime import datetime
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from anomod.io.lfs import is_lfs_pointer
from anomod.schemas import ApiBatch


def _ts(s) -> float:
    if isinstance(s, (int, float)):
        return float(s)
    try:
        return datetime.fromisoformat(str(s)).timestamp()
    except ValueError:
        return 0.0


def load_api_jsonl(path: Path) -> Optional[ApiBatch]:
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    endpoints: Dict[str, int] = {}
    ep_c: List[int] = []
    t_c: List[float] = []
    st_c: List[int] = []
    lat_c: List[float] = []
    cl_c: List[int] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            ep_c.append(endpoints.setdefault(str(rec.get("endpoint", "")), len(endpoints)))
            t_c.append(_ts(rec.get("timestamp", 0)))
            st_c.append(int(rec.get("status_code", 0) or 0))
            lat_c.append(float(rec.get("latency_ms", 0) or 0))
            cl_c.append(int(rec.get("content_length", 0) or 0))
    if not ep_c:
        return None
    return ApiBatch(
        endpoint=np.array(ep_c, np.int32), t_s=np.array(t_c, np.float64),
        status=np.array(st_c, np.int16), latency_ms=np.array(lat_c, np.float32),
        content_length=np.array(cl_c, np.int32), endpoints=tuple(endpoints))


def find_api_artifact(exp_dir: Path) -> Optional[Path]:
    exp_dir = Path(exp_dir)
    p = exp_dir / "openapi_responses.jsonl"           # SN
    if p.is_file():
        return p
    cands = sorted(exp_dir.glob("*/api_responses.jsonl"))  # TT date subdir
    return cands[-1] if cands else None


def write_api_jsonl(batch: ApiBatch, path: Path) -> None:
    """Materialize an ApiBatch in the reference JSONL shape."""
    with open(path, "w") as f:
        for i in range(batch.n_records):
            f.write(json.dumps({
                "timestamp": datetime.fromtimestamp(float(batch.t_s[i])).isoformat(),
                "endpoint": batch.endpoints[int(batch.endpoint[i])],
                "method": "GET",
                "status_code": int(batch.status[i]),
                "latency_ms": round(float(batch.latency_ms[i]), 2),
                "content_length": int(batch.content_length[i]),
            }) + "\n")


def analyze_api_batch(batch: ApiBatch) -> dict:
    """Traffic analysis over an ApiBatch — the analyzer analog of
    analyze_http_traffic.py (tshark post-processor: request/status/method
    distributions) and the monitor's endpoint_performance.json
    (enhanced_openapi_monitor.py:318-397)."""
    lat = batch.latency_ms.astype(float)
    status_counts = {int(c): int((batch.status == c).sum())
                     for c in np.unique(batch.status)}
    per_endpoint = {}
    for i, ep in enumerate(batch.endpoints):
        m = batch.endpoint == i
        if not m.any():
            continue
        el = lat[m]
        per_endpoint[ep] = {
            "requests": int(m.sum()),
            "error_rate": float((batch.status[m] >= 400).mean()),
            "avg_latency_ms": float(el.mean()),
            "p95_latency_ms": float(np.percentile(el, 95)),
            "p99_latency_ms": float(np.percentile(el, 99)),
        }
    return {
        "total_requests": int(batch.n_records),
        "status_distribution": status_counts,
        "method_distribution": {"GET": int(batch.n_records)},
        "error_rate": float((batch.status >= 400).mean()),
        "avg_latency_ms": float(lat.mean()) if len(lat) else 0.0,
        "endpoint_performance": per_endpoint,
    }
