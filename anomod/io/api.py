"""API-response JSONL loader → ApiBatch.

Record contract (enhanced_openapi_monitor.py:155-169): one JSON object per
line with ``timestamp`` (ISO), ``endpoint``, ``method``, ``status_code``,
``latency_ms``, ``content_length``, ...  SN layout:
``<exp>/openapi_responses.jsonl``; TT layout: ``<exp>/<YYYYMMDD>/api_responses.jsonl``.
"""

from __future__ import annotations

import json
from datetime import datetime
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from anomod.io.lfs import is_lfs_pointer
from anomod.schemas import ApiBatch

#: Ingest-cache key component (anomod.io.cache): bump when this module's
#: parsing semantics change, invalidating exactly the api entries.
LOADER_VERSION = 1


def _ts(s) -> float:
    if isinstance(s, (int, float)):
        return float(s)
    try:
        return datetime.fromisoformat(str(s)).timestamp()
    except ValueError:
        return 0.0


def load_api_jsonl(path: Path) -> Optional[ApiBatch]:
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    endpoints: Dict[str, int] = {}
    ep_c: List[int] = []
    t_c: List[float] = []
    st_c: List[int] = []
    lat_c: List[float] = []
    cl_c: List[int] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            ep_c.append(endpoints.setdefault(str(rec.get("endpoint", "")), len(endpoints)))
            t_c.append(_ts(rec.get("timestamp", 0)))
            st_c.append(int(rec.get("status_code", 0) or 0))
            lat_c.append(float(rec.get("latency_ms", 0) or 0))
            cl_c.append(int(rec.get("content_length", 0) or 0))
    if not ep_c:
        return None
    return ApiBatch(
        endpoint=np.array(ep_c, np.int32), t_s=np.array(t_c, np.float64),
        status=np.array(st_c, np.int16), latency_ms=np.array(lat_c, np.float32),
        content_length=np.array(cl_c, np.int32), endpoints=tuple(endpoints))


def find_api_artifact(exp_dir: Path) -> Optional[Path]:
    exp_dir = Path(exp_dir)
    p = exp_dir / "openapi_responses.jsonl"           # SN
    if p.is_file():
        return p
    cands = sorted(exp_dir.glob("*/api_responses.jsonl"))  # TT date subdir
    return cands[-1] if cands else None


def _endpoint_method(endpoint: str) -> str:
    """Endpoints recorded as "METHOD /path" carry their method; bare paths
    default to GET (the monitor's probe default)."""
    head = endpoint.split(" ", 1)[0]
    return head if head.isupper() and head.isalpha() else "GET"


def write_api_jsonl(batch: ApiBatch, path: Path) -> None:
    """Materialize an ApiBatch in the reference JSONL shape."""
    methods = [_endpoint_method(e) for e in batch.endpoints]
    with open(path, "w") as f:
        for i in range(batch.n_records):
            f.write(json.dumps({
                "timestamp": datetime.fromtimestamp(float(batch.t_s[i])).isoformat(),
                "endpoint": batch.endpoints[int(batch.endpoint[i])],
                "method": methods[int(batch.endpoint[i])],
                "status_code": int(batch.status[i]),
                "latency_ms": round(float(batch.latency_ms[i]), 2),
                "content_length": int(batch.content_length[i]),
            }) + "\n")


def analyze_api_batch(batch: ApiBatch) -> dict:
    """Traffic analysis over an ApiBatch — the analyzer analog of
    analyze_http_traffic.py (tshark post-processor: request/status/method
    distributions) and the monitor's endpoint_performance.json
    (enhanced_openapi_monitor.py:318-397)."""
    lat = batch.latency_ms.astype(float)
    status_counts = {int(c): int((batch.status == c).sum())
                     for c in np.unique(batch.status)}
    per_endpoint = {}
    methods: Dict[str, int] = {}
    counts = np.bincount(batch.endpoint, minlength=len(batch.endpoints))
    for i, ep in enumerate(batch.endpoints):
        methods[_endpoint_method(ep)] = (
            methods.get(_endpoint_method(ep), 0) + int(counts[i]))
        m = batch.endpoint == i
        if not m.any():
            continue
        el = lat[m]
        per_endpoint[ep] = {
            "requests": int(m.sum()),
            "error_rate": float((batch.status[m] >= 400).mean()),
            "avg_latency_ms": float(el.mean()),
            "p95_latency_ms": float(np.percentile(el, 95)),
            "p99_latency_ms": float(np.percentile(el, 99)),
        }
    return {
        "total_requests": int(batch.n_records),
        "status_distribution": status_counts,
        "method_distribution": methods,
        "error_rate": float((batch.status >= 400).mean()),
        "avg_latency_ms": float(lat.mean()) if len(lat) else 0.0,
        "endpoint_performance": per_endpoint,
    }


def write_api_artifact_family(batch: ApiBatch, adir: Path) -> None:
    """Materialize the full SN api_responses artifact family
    (enhanced_openapi_monitor.py:272,359,364,390 + the orchestrator's
    traffic_analysis.json, collect_openapi_response.sh:117-142):
    openapi_responses.jsonl, response_summary.json, endpoint_performance.json,
    status_code_distribution.csv, traffic_analysis.json."""
    adir = Path(adir)
    adir.mkdir(parents=True, exist_ok=True)
    write_api_jsonl(batch, adir / "openapi_responses.jsonl")
    lat = batch.latency_ms
    (adir / "response_summary.json").write_text(json.dumps({
        "total_requests": int(batch.n_records),
        "status_codes": {str(c): int((batch.status == c).sum())
                         for c in np.unique(batch.status)},
        "avg_latency_ms": float(lat.mean()) if len(lat) else 0.0,
        "p95_latency_ms": float(np.percentile(lat, 95)) if len(lat) else 0.0,
        "p99_latency_ms": float(np.percentile(lat, 99)) if len(lat) else 0.0,
    }))
    analysis = analyze_api_batch(batch)
    (adir / "traffic_analysis.json").write_text(json.dumps(analysis))
    (adir / "endpoint_performance.json").write_text(
        json.dumps(analysis["endpoint_performance"]))
    with open(adir / "status_code_distribution.csv", "w") as f:
        f.write("status_code,count\n")
        for c in np.unique(batch.status):
            f.write(f"{int(c)},{int((batch.status == c).sum())}\n")
