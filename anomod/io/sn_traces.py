"""SN / Jaeger trace loaders → SpanBatch (JSON and flattened CSV).

JSON: the merged Jaeger API dump ``all_traces.json`` — ``{"data": [{traceID,
processes{pid:{serviceName}}, spans[{spanID, processID, operationName,
startTime(µs), duration(µs), references[{refType:CHILD_OF, spanID}], tags}]}]}``
(collect_trace.sh:40-70 produces it; jaeger_to_csv.py:20-74 is the flattener).

CSV: ``all_traces.csv`` with the 13-column contract of jaeger_to_csv.py:76-90.
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from anomod.io.lfs import is_lfs_pointer
from anomod.schemas import (KIND_ENTRY, KIND_EXIT, KIND_LOCAL, SpanBatch,
                            empty_span_batch)

#: Ingest-cache key component (anomod.io.cache): bump when this module's
#: parsing semantics change, invalidating exactly the SN trace entries.
LOADER_VERSION = 1

_JKIND = {"server": KIND_ENTRY, "client": KIND_EXIT, "consumer": KIND_ENTRY,
          "producer": KIND_EXIT}


def load_jaeger_json(path: Path) -> Optional[SpanBatch]:
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    return spans_from_jaeger(doc)


def spans_from_jaeger(doc: dict) -> SpanBatch:
    data = doc.get("data", [])
    n = sum(len(t.get("spans", [])) for t in data)
    if n == 0:
        return empty_span_batch()

    services: Dict[str, int] = {}
    endpoints: Dict[str, int] = {}
    trace_ids: Dict[str, int] = {}
    trace_c = np.zeros(n, np.int32)
    service_c = np.zeros(n, np.int32)
    endpoint_c = np.zeros(n, np.int32)
    start_c = np.zeros(n, np.int64)
    dur_c = np.zeros(n, np.int64)
    err_c = np.zeros(n, np.bool_)
    status_c = np.zeros(n, np.int16)
    kind_c = np.zeros(n, np.int8)
    parent_c = np.full(n, -1, np.int32)

    row_of: Dict[tuple, int] = {}
    pending = []
    r = 0
    for t in data:
        tid = t.get("traceID", "")
        t_idx = trace_ids.setdefault(tid, len(trace_ids))
        proc_svc = {pid: info.get("serviceName", "")
                    for pid, info in (t.get("processes") or {}).items()}
        for sp in t.get("spans", []):
            row_of[(t_idx, sp.get("spanID", ""))] = r
            trace_c[r] = t_idx
            svc = proc_svc.get(sp.get("processID", ""), "")
            service_c[r] = services.setdefault(svc, len(services))
            endpoint_c[r] = endpoints.setdefault(sp.get("operationName", ""),
                                                 len(endpoints))
            start_c[r] = int(sp.get("startTime", 0))
            dur_c[r] = int(sp.get("duration", 0))
            kind = KIND_LOCAL
            status = 0
            err = False
            for tag in sp.get("tags", []):
                k, v = tag.get("key", ""), tag.get("value", "")
                if k == "http.status_code":
                    try:
                        status = int(v)
                    except (TypeError, ValueError):
                        status = 0
                elif k == "span.kind":
                    kind = _JKIND.get(str(v), KIND_LOCAL)
                elif k == "error":
                    err = bool(v) and str(v).lower() != "false"
            err_c[r] = err or status >= 500
            status_c[r] = status
            kind_c[r] = kind
            # parent: first CHILD_OF reference (jaeger_to_csv.py:35-38)
            for ref in sp.get("references", []):
                if ref.get("refType") == "CHILD_OF":
                    pending.append((r, t_idx, ref.get("spanID", "")))
                    break
            r += 1

    for row, t_idx, psid in pending:
        parent_c[row] = row_of.get((t_idx, psid), -1)

    return SpanBatch(
        trace=trace_c, parent=parent_c, service=service_c, endpoint=endpoint_c,
        start_us=start_c, duration_us=dur_c, is_error=err_c, status=status_c,
        kind=kind_c,
        services=tuple(services), endpoints=tuple(endpoints),
        trace_ids=tuple(trace_ids),
    ).validate()


def load_jaeger_csv(path: Path) -> Optional[SpanBatch]:
    """Load the 13-column flattened CSV (jaeger_to_csv.py:76-90)."""
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    services: Dict[str, int] = {}
    endpoints: Dict[str, int] = {}
    trace_ids: Dict[str, int] = {}
    rows = []
    with open(path, newline="") as f:
        for rec in csv.DictReader(f):
            rows.append(rec)
    if not rows:
        return empty_span_batch()
    n = len(rows)
    trace_c = np.zeros(n, np.int32)
    service_c = np.zeros(n, np.int32)
    endpoint_c = np.zeros(n, np.int32)
    start_c = np.zeros(n, np.int64)
    dur_c = np.zeros(n, np.int64)
    err_c = np.zeros(n, np.bool_)
    status_c = np.zeros(n, np.int16)
    kind_c = np.full(n, KIND_LOCAL, np.int8)
    parent_c = np.full(n, -1, np.int32)
    row_of: Dict[tuple, int] = {}
    for r, rec in enumerate(rows):
        t_idx = trace_ids.setdefault(rec.get("trace_id", ""), len(trace_ids))
        trace_c[r] = t_idx
        row_of[(t_idx, rec.get("span_id", ""))] = r
        service_c[r] = services.setdefault(rec.get("service", ""), len(services))
        endpoint_c[r] = endpoints.setdefault(rec.get("operation", ""), len(endpoints))
        # start_time is a wall string; CSV keeps duration_us authoritative
        dur_c[r] = int(float(rec.get("duration_us") or 0))
        try:
            status_c[r] = int(float(rec.get("http_status_code") or 0))
        except ValueError:
            status_c[r] = 0
        err_c[r] = status_c[r] >= 500
    for r, rec in enumerate(rows):
        psid = rec.get("parent_span_id", "")
        if psid:
            parent_c[r] = row_of.get((int(trace_c[r]), psid), -1)
    # synthesize monotone start order from file order (CSV drops µs epoch)
    start_c[:] = np.arange(n, dtype=np.int64)
    return SpanBatch(
        trace=trace_c, parent=parent_c, service=service_c, endpoint=endpoint_c,
        start_us=start_c, duration_us=dur_c, is_error=err_c, status=status_c,
        kind=kind_c, services=tuple(services), endpoints=tuple(endpoints),
        trace_ids=tuple(trace_ids),
    ).validate()


def find_trace_artifact(exp_dir: Path) -> Optional[Path]:
    """SN layout: all_traces.{json,csv} (collect_trace.sh:40-70)."""
    for name in ("all_traces.json", "all_traces.csv"):
        p = Path(exp_dir) / name
        if p.is_file():
            return p
    return None


_CSV_COLUMNS = ("trace_id", "span_id", "parent_span_id", "service", "operation",
                "start_time", "duration_us", "http_status_code", "http_method",
                "http_url", "component", "tags", "logs")


def write_jaeger_csv(batch: SpanBatch, path: Path) -> None:
    """Flatten a SpanBatch to the reference's 13-column CSV
    (jaeger_to_csv.py:76-90) — the jaeger_to_csv flattener equivalent."""
    from datetime import datetime, timezone
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(_CSV_COLUMNS)
        for i in range(batch.n_spans):
            par = int(batch.parent[i])
            start = datetime.fromtimestamp(
                batch.start_us[i] / 1e6, tz=timezone.utc
            ).strftime("%Y-%m-%d %H:%M:%S.%f")
            status = int(batch.status[i])
            w.writerow([
                batch.trace_ids[int(batch.trace[i])], f"s{i:08x}",
                f"s{par:08x}" if par >= 0 else "",
                batch.services[int(batch.service[i])],
                batch.endpoints[int(batch.endpoint[i])],
                start, int(batch.duration_us[i]),
                status if status else "", "", "", "thrift",
                json.dumps({"error": bool(batch.is_error[i])}), "",
            ])
