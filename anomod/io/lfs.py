"""Git-LFS pointer detection.

Most SN_data/TT_data payloads in the reference checkout are LFS pointer stubs
(.gitattributes:1-5), e.g. a 3-line file starting with
``version https://git-lfs.github.com/spec/v1``.  Loaders detect these and fall
back to the deterministic synthetic generator (anomod.synth).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

_LFS_MAGIC = b"version https://git-lfs.github.com/spec/v1"


def is_lfs_pointer(path: Path) -> bool:
    try:
        if path.stat().st_size > 512:
            return False
        with open(path, "rb") as f:
            return f.read(len(_LFS_MAGIC)) == _LFS_MAGIC
    except OSError:
        return False


def lfs_real_size(path: Path) -> Optional[int]:
    """Declared payload size from the pointer file, if this is one."""
    if not is_lfs_pointer(path):
        return None
    for line in path.read_text().splitlines():
        if line.startswith("size "):
            return int(line.split()[1])
    return None


def read_text_or_none(path: Path) -> Optional[str]:
    """Read text content; None if missing or an LFS pointer stub."""
    p = Path(path)
    if not p.is_file() or is_lfs_pointer(p):
        return None
    return p.read_text(errors="replace")
