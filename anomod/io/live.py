"""Live-transport collector adapters: HTTP clients for the four backends
the reference's collection toolchain talks to, emitting EXACTLY the artifact
schemas the offline loaders consume.

The reference's collectors are thin clients against live observability
infra — Prometheus ``query_range``
(SN_collection-scripts/Dataset/metric_data/fetch_prometheus_metrics.py:9-80),
Jaeger REST fanned out per service with traceID dedup
(SN_collection-scripts/Dataset/trace_data/collect_trace.sh:25-58),
SkyWalking GraphQL with pagination and linear backoff
(TT_collection-scripts/T-Dataset/trace_collector.py:261-396), and raw
Elasticsearch ``sw_segment-*`` queries
(TT_collection-scripts/T-Dataset/enhanced_trace_collector.py:56-100).
This module is the live half of the corresponding loader modules: each
client's ``collect*`` writes a file the matching ``anomod.io.*`` loader
round-trips bit-compatibly, so a collection pointed at real infra drops
straight into the campaign tree layout.

Design notes (fresh, not a port):
  - ONE transport (:class:`HttpTransport`, urllib-based — zero new deps)
    carries the retry/backoff policy for all four protocols; the reference
    re-implements retries per collector.  Backoff is the reference's
    policy: wait ``min(3·attempt, 10)`` seconds between attempts
    (trace_collector.py:279-291).
  - Clients return columnar-friendly plain data and leave graph resolution
    to the loaders (anomod.io.tt_traces does vectorized parent resolution;
    the reference resolves per-span at collect time).
  - Everything is testable against in-process stub HTTP servers
    (tests/test_live.py) — no live infra needed to verify the wire
    behavior, which is how this module stays covered in CI.
"""

from __future__ import annotations

import csv
import dataclasses
import json
import time
import urllib.error
import urllib.parse
import urllib.request
from datetime import datetime, timezone
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class TransportError(RuntimeError):
    """A request failed permanently (retries exhausted or server-side
    error payload)."""


@dataclasses.dataclass
class HttpTransport:
    """Bounded-retry JSON-over-HTTP transport shared by all clients.

    ``sleep`` is injectable so tests assert the backoff schedule without
    waiting it out.  GET when ``payload is None``, POST (JSON body)
    otherwise."""
    timeout: float = 30.0
    max_retries: int = 3
    sleep: Callable[[float], None] = time.sleep

    def request_json(self, url: str, payload: Optional[dict] = None,
                     params: Optional[dict] = None):
        return self._request(url, payload, params,
                             lambda raw: json.loads(raw.decode()))

    def request_text(self, url: str, params: Optional[dict] = None) -> str:
        """GET -> decoded body text (the Prometheus exposition-format
        scrape path; same retry/backoff policy as the JSON surface)."""
        return self._request(url, None, params,
                             lambda raw: raw.decode(errors="replace"))

    def _request(self, url: str, payload: Optional[dict],
                 params: Optional[dict], decode: Callable[[bytes], object]):
        if params:
            url = f"{url}?{urllib.parse.urlencode(params)}"
        last: Optional[Exception] = None
        for attempt in range(1, self.max_retries + 1):
            try:
                if payload is None:
                    req = urllib.request.Request(url)
                else:
                    req = urllib.request.Request(
                        url, data=json.dumps(payload).encode(),
                        headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    # decode INSIDE the try: a truncated/garbled body is
                    # retried like any other transient wire fault
                    return decode(r.read())
            except urllib.error.HTTPError as e:
                if 400 <= e.code < 500:
                    # client errors (bad PromQL, malformed GraphQL) are
                    # permanent: retrying burns the whole backoff schedule
                    # and buries the real error class.  The body carries
                    # the server's actual diagnostic (e.g. the PromQL
                    # parse error) — surface it, truncated.
                    try:
                        body = e.read().decode(errors="replace")[:500]
                    except Exception:
                        body = ""
                    raise TransportError(
                        f"request to {url.split('?')[0]} rejected: "
                        f"HTTP {e.code} {e.reason}"
                        + (f": {body}" if body else "")) from e
                last = e          # 5xx: server-side, worth retrying
                if attempt < self.max_retries:
                    self.sleep(min(3.0 * attempt, 10.0))
            except Exception as e:  # timeouts, connection errors, bad JSON
                last = e
                if attempt < self.max_retries:
                    self.sleep(min(3.0 * attempt, 10.0))
        raise TransportError(
            f"request to {url.split('?')[0]} failed after "
            f"{self.max_retries} attempts: {last}") from last


@dataclasses.dataclass
class CollectReport:
    """What a ``collect*`` call produced — the validator-friendly summary
    (the reference's collectors log equivalent counts to stdout)."""
    kind: str
    files: Tuple[str, ...] = ()
    n_records: int = 0
    n_skipped: int = 0
    notes: Tuple[str, ...] = ()

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Prometheus
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PrometheusClient:
    """``/api/v1/query_range`` client emitting the SN per-query CSV shape
    (``timestamp,value,metric,<label cols>`` — fetch_prometheus_metrics.py:
    44-71) and the TT long-CSV shape (metric_collector.py:431-443), both of
    which ``anomod.io.metrics`` loads."""
    base_url: str
    transport: HttpTransport = dataclasses.field(default_factory=HttpTransport)

    def query_range(self, query: str, start_s: float, end_s: float,
                    step: str = "15s") -> List[Tuple[float, float, Dict[str, str]]]:
        """Run one range query -> [(epoch_s, value, labels)] rows.

        Mirrors the reference's handling: a non-"success" status is an
        error; an empty result set is NOT (returns [])."""
        doc = self.transport.request_json(
            f"{self.base_url}/api/v1/query_range",
            params={"query": query, "start": start_s, "end": end_s,
                    "step": step})
        if doc.get("status") != "success":
            raise TransportError(
                f"prometheus error for {query!r}: "
                f"{doc.get('error', 'unknown error')}")
        rows: List[Tuple[float, float, Dict[str, str]]] = []
        for result in doc.get("data", {}).get("result", []):
            labels = dict(result.get("metric", {}))
            for ts, val in result.get("values", []):
                try:
                    rows.append((float(ts), float(val), labels))
                except (TypeError, ValueError):
                    continue
        return rows

    def query_range_since(
            self, query: str, since_s: float, until_s: float,
            step: str = "15s",
    ) -> Tuple[List[Tuple[float, float, Dict[str, str]]], float]:
        """Watermark-tailed incremental poll for the live feed
        (anomod.serve.feed).

        Runs ``query_range(query, since_s, until_s)`` and keeps only the
        rows STRICTLY past the ``since_s`` watermark, so back-to-back
        polls never re-deliver a sample (query_range windows are
        inclusive on both ends).  Returns ``(fresh_rows,
        new_watermark)`` where the new watermark is the max delivered
        timestamp (or ``since_s`` unchanged on an empty poll) — always
        monotone."""
        rows = self.query_range(query, since_s, until_s, step)
        fresh = [(ts, val, labels) for ts, val, labels in rows
                 if ts > since_s]
        mark = max([since_s] + [ts for ts, _, _ in fresh])
        return fresh, mark

    def write_query_csv(self, query: str, metric_name: str, out_dir: Path,
                        start_s: float, end_s: float,
                        step: str = "15s") -> Optional[Tuple[Path, int]]:
        """One SN per-query artifact: ``<metric_name>.csv`` with columns
        ``timestamp,value,metric,<sorted label cols>``; no file when the
        query returned no data (the reference skips those with a
        warning).  Returns ``(path, n_rows)``."""
        rows = self.query_range(query, start_s, end_s, step)
        if not rows:
            return None
        label_cols = sorted({k for _, _, labels in rows for k in labels})
        out_dir = Path(out_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        path = out_dir / f"{metric_name}.csv"
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["timestamp", "value", "metric"] + label_cols)
            for ts, val, labels in rows:
                # UTC, not local: artifacts from collectors in different
                # timezones must be byte-comparable for the same data
                stamp = datetime.fromtimestamp(
                    ts, tz=timezone.utc).strftime("%Y-%m-%d %H:%M:%S")
                lab = ",".join(f'{k}="{v}"'
                               for k, v in sorted(labels.items()))
                w.writerow([stamp, val, lab]
                           + [labels.get(k, "") for k in label_cols])
        return path, len(rows)

    def collect_sn(self, queries: Dict[str, str], out_dir: Path,
                   start_s: float, end_s: float,
                   step: str = "15s") -> CollectReport:
        """SN catalog sweep: one CSV per (name -> PromQL) entry into
        ``out_dir`` — collect_metric.sh's fan-out, with the catalog carried
        as data (``anomod.metrics_catalog.SN_METRIC_FILES``)."""
        files, skipped, n = [], 0, 0
        for name, query in queries.items():
            wrote = self.write_query_csv(query, name, out_dir, start_s,
                                         end_s, step)
            if wrote is None:
                skipped += 1
                continue
            path, n_rows = wrote
            files.append(str(path))
            n += n_rows
        return CollectReport(kind="prometheus_sn", files=tuple(files),
                             n_records=n, n_skipped=skipped)

    def collect_tt(self, queries: Sequence[str], out_path: Path,
                   start_s: float, end_s: float,
                   step: str = "15s") -> CollectReport:
        """TT long-CSV sweep: every query appended into ONE CSV with the
        fixed columns ``metric_name,timestamp,datetime,value`` followed by
        the sorted union of label columns (``__name__`` excluded), with
        ``metric_name`` the raw query string — metric_collector.py:431-466
        row semantics; ``anomod.io.metrics.load_tt_metric_csv`` reads it
        back."""
        all_rows: List[dict] = []
        skipped = 0
        for query in queries:
            rows = self.query_range(query, start_s, end_s, step)
            if not rows:
                skipped += 1
                continue
            for ts, val, labels in rows:
                row = {"metric_name": query, "timestamp": ts,
                       "datetime": datetime.fromtimestamp(
                           ts, tz=timezone.utc).isoformat(),
                       "value": val}
                row.update({k: v for k, v in labels.items()
                            if k != "__name__"})
                all_rows.append(row)
        fixed = ["metric_name", "timestamp", "datetime", "value"]
        label_cols = sorted({k for r in all_rows for k in r}
                            - set(fixed))
        out_path = Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        with open(out_path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=fixed + label_cols,
                               restval="")
            w.writeheader()
            w.writerows(all_rows)
        return CollectReport(kind="prometheus_tt",
                             files=(str(out_path),), n_records=len(all_rows),
                             n_skipped=skipped)


# ---------------------------------------------------------------------------
# Jaeger
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JaegerClient:
    """Jaeger query-service REST client (SN trace path).

    ``collect_all`` is collect_trace.sh:25-58 as a function: enumerate
    services, fetch each service's recent traces, merge unique-by-traceID,
    write one ``{"data": [...]}`` doc that ``anomod.io.sn_traces.
    load_jaeger_json`` consumes."""
    base_url: str
    transport: HttpTransport = dataclasses.field(default_factory=HttpTransport)

    def services(self) -> List[str]:
        doc = self.transport.request_json(f"{self.base_url}/api/services")
        return list(doc.get("data") or [])

    def traces(self, service: str, limit: int = 2000,
               lookback_ms: int = 3_600_000,
               now_s: Optional[float] = None) -> List[dict]:
        # lookback matches the reference's request line
        # (collect_trace.sh:48); start/end in epoch µs are ALSO sent
        # because some query-service versions ignore lookback without an
        # explicit window — both derive from the same lookback_ms
        now = time.time() if now_s is None else now_s
        doc = self.transport.request_json(
            f"{self.base_url}/api/traces",
            params={"service": service, "limit": limit,
                    "lookback": lookback_ms,
                    "start": int((now - lookback_ms / 1000.0) * 1e6),
                    "end": int(now * 1e6)})
        return list(doc.get("data") or [])

    def traces_since(self, service: str, since_us: int, until_us: int,
                     limit: int = 2000) -> Tuple[List[dict], int]:
        """Watermark-tailed incremental poll for the live feed
        (anomod.serve.feed).

        Queries the explicit ``[since_us, until_us]`` window (epoch µs)
        and keeps only traces whose LATEST span starts strictly past the
        watermark — a trace is delivered once, on the poll that first
        sees it complete up to that point.  Returns ``(fresh_traces,
        new_watermark_us)``; the watermark is the max span startTime
        delivered (unchanged on an empty poll) — always monotone."""
        doc = self.transport.request_json(
            f"{self.base_url}/api/traces",
            params={"service": service, "limit": limit,
                    "start": int(since_us), "end": int(until_us)})
        fresh: List[dict] = []
        mark = int(since_us)
        for tr in doc.get("data") or []:
            starts = [int(sp.get("startTime", 0))
                      for sp in (tr.get("spans") or [])]
            if not starts or max(starts) <= since_us:
                continue
            fresh.append(tr)
            mark = max(mark, max(starts))
        return fresh, mark

    def collect_all(self, out_path: Path, limit: int = 2000,
                    lookback_ms: int = 3_600_000) -> CollectReport:
        merged: Dict[str, dict] = {}
        n_dup = 0
        for svc in self.services():
            for tr in self.traces(svc, limit=limit, lookback_ms=lookback_ms):
                tid = tr.get("traceID", "")
                if tid in merged:
                    n_dup += 1
                else:
                    merged[tid] = tr
        out_path = Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump({"data": list(merged.values())}, f)
        return CollectReport(kind="jaeger", files=(str(out_path),),
                             n_records=len(merged), n_skipped=n_dup,
                             notes=(f"deduped {n_dup} cross-service "
                                    f"duplicates",))


# ---------------------------------------------------------------------------
# SkyWalking GraphQL
# ---------------------------------------------------------------------------

# The GraphQL query surface, reduced to exactly the fields the artifact
# schema needs (the public SkyWalking OAP API; trace_collector.py:139-178
# queries the same endpoints).
_SW_TRACE_LIST = """
query queryBasicTraces($condition: TraceQueryCondition!) {
  data: queryBasicTraces(condition: $condition) {
    total
    traces { traceIds duration start isError endpointNames }
  }
}
""".strip()

_SW_TRACE_DETAIL = """
query queryTrace($traceId: ID!) {
  trace: queryTrace(traceId: $traceId) {
    spans {
      traceId segmentId spanId parentSpanId serviceCode
      startTime endTime endpointName type peer component isError layer
      tags { key value }
      refs { traceId parentSegmentId parentSpanId type }
    }
  }
}
""".strip()


@dataclasses.dataclass
class SkyWalkingClient:
    """SkyWalking OAP GraphQL client (TT trace path): paginated summary
    listing with traceID dedup, per-trace span fetch, and an artifact
    builder emitting the collector JSON schema ``anomod.io.tt_traces``
    loads (behavioral parity: trace_collector.py:296-396 fetch,
    :552-584 artifact)."""
    graphql_url: str
    transport: HttpTransport = dataclasses.field(default_factory=HttpTransport)

    def _post(self, query: str, variables: dict) -> dict:
        doc = self.transport.request_json(
            self.graphql_url, payload={"query": query,
                                       "variables": variables})
        if doc.get("errors"):
            raise TransportError(f"graphql error: {doc['errors']}")
        return doc.get("data") or {}

    def trace_summaries(self, limit: int = 1000, hours_back: float = 1.0,
                        page_size: int = 200,
                        now_s: Optional[float] = None) -> List[dict]:
        """Paginated ``queryBasicTraces`` sweep -> summary dicts, deduped
        by first traceId; stops on a short page or at ``limit``.  The
        query window is minute-grained under 12 h lookback, hour-grained
        beyond (the reference's step selection).  ``limit`` must be >= 1:
        there is no unlimited mode (a server that always returns full
        pages would otherwise paginate forever)."""
        if limit < 1:
            raise ValueError(f"limit must be >= 1, got {limit}")
        page_size = max(1, min(page_size, limit))
        now = time.time() if now_s is None else now_s
        start = now - max(hours_back, 0.1) * 3600.0
        step = "MINUTE" if hours_back <= 12 else "HOUR"
        fmt = "%Y-%m-%d %H%M" if step == "MINUTE" else "%Y-%m-%d %H"
        condition_base = {
            # queryDuration strings are rendered in UTC: the OAP server
            # interprets them in its own timezone, so a deterministic
            # rendering (rather than the collector host's local TZ) is the
            # only choice that makes the same call reproducible everywhere
            "queryDuration": {
                "start": datetime.fromtimestamp(
                    start, tz=timezone.utc).strftime(fmt),
                "end": datetime.fromtimestamp(
                    now, tz=timezone.utc).strftime(fmt),
                "step": step,
            },
            "traceState": "ALL",
            "queryOrder": "BY_START_TIME",
            "paging": {"pageNum": 1, "pageSize": page_size},
        }
        out: List[dict] = []
        seen: set = set()
        page = 1
        while len(out) < limit:
            condition = dict(condition_base,
                             paging={"pageNum": page, "pageSize": page_size})
            data = self._post(_SW_TRACE_LIST, {"condition": condition})
            traces = (data.get("data") or {}).get("traces") or []
            if not traces:
                break
            new_here = 0
            for entry in traces:
                tids = entry.get("traceIds") or []
                if not tids or tids[0] in seen:
                    continue
                seen.add(tids[0])
                new_here += 1
                out.append(dict(entry, traceIds=tids))
                if len(out) >= limit:
                    break
            if len(traces) < page_size:
                break
            if new_here == 0:
                # a full page of already-seen traces means the server is
                # not honoring pageNum (or the window is being re-served);
                # without this break such a server paginates forever
                break
            page += 1
        return out[:limit]

    def trace_spans(self, trace_id: str) -> List[dict]:
        data = self._post(_SW_TRACE_DETAIL, {"traceId": trace_id})
        return list((data.get("trace") or {}).get("spans") or [])

    @staticmethod
    def build_artifact(experiment: str,
                       traces: List[Tuple[dict, List[dict]]],
                       collection_hours: float = 24) -> dict:
        """Raw GraphQL (summary, spans) pairs -> the collector JSON schema.

        Node identity is ``segment_id:span_id``; same-segment parents keep
        ``parent_span_id``, cross-segment parents ride ``refs`` — the
        loader (anomod.io.tt_traces) resolves both vectorized."""
        out_traces: List[dict] = []
        all_services: set = set()
        n_spans = 0
        for summary, spans in traces:
            tids = summary.get("traceIds") or [""]
            tid = tids[0]
            arts: List[dict] = []
            roots: List[str] = []
            for sp in spans:
                seg = str(sp.get("segmentId", ""))
                sid = int(sp.get("spanId", 0))
                psid = int(sp.get("parentSpanId", -1))
                node = f"{seg}:{sid}"
                refs = [dict(r) for r in (sp.get("refs") or [])]
                parent_node = None
                if psid >= 0:
                    parent_node = f"{seg}:{psid}"
                elif refs:
                    parent_node = (f"{refs[0].get('parentSegmentId', '')}:"
                                   f"{refs[0].get('parentSpanId', -1)}")
                else:
                    roots.append(node)
                start_ms = int(sp.get("startTime", 0))
                end_ms = int(sp.get("endTime", start_ms))
                tags_map = {t.get("key", ""): t.get("value", "")
                            for t in (sp.get("tags") or [])}
                svc = str(sp.get("serviceCode", ""))
                all_services.add(svc)
                arts.append({
                    "node_id": node,
                    "trace_id": str(sp.get("traceId", tid)),
                    "segment_id": seg,
                    "span_id": sid,
                    "parent_span_id": psid,
                    "parent_node_id": parent_node,
                    "service_code": svc,
                    "start_timestamp_ms": start_ms,
                    "end_timestamp_ms": end_ms,
                    "duration_ms": max(0, end_ms - start_ms),
                    "endpoint_name": sp.get("endpointName") or "",
                    "type": sp.get("type") or "Local",
                    "peer": sp.get("peer"),
                    "component": sp.get("component"),
                    "layer": sp.get("layer"),
                    "is_error": bool(sp.get("isError", False)),
                    "tags": [{"key": k, "value": v}
                             for k, v in tags_map.items()],
                    "tags_map": tags_map,
                    "refs": refs,
                })
            n_spans += len(arts)
            out_traces.append({
                "summary": {"trace_ids": tids,
                            "duration": int(summary.get("duration", 0)),
                            "is_error": bool(summary.get("isError", False))},
                "trace_id": tid,
                "span_count": len(arts),
                "services_involved":
                    sorted({a["service_code"] for a in arts}),
                "root_span_node_ids": roots,
                "spans": arts,
            })
        return {
            "metadata": {
                "experiment": experiment,
                "collection_hours": collection_hours,
                "trace_count": len(out_traces),
                "span_count": n_spans,
                "services": sorted(all_services),
                "generator": "anomod.io.live.SkyWalkingClient",
            },
            "traces": out_traces,
        }

    def collect(self, out_path: Path, experiment: str, limit: int = 1000,
                hours_back: float = 1.0, page_size: int = 200,
                now_s: Optional[float] = None) -> CollectReport:
        summaries = self.trace_summaries(limit=limit, hours_back=hours_back,
                                         page_size=page_size, now_s=now_s)
        pairs: List[Tuple[dict, List[dict]]] = []
        empty = 0
        for s in summaries:
            spans = self.trace_spans((s.get("traceIds") or [""])[0])
            if not spans:
                empty += 1
                continue
            pairs.append((s, spans))
        doc = self.build_artifact(experiment, pairs,
                                  collection_hours=hours_back)
        out_path = Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f)
        return CollectReport(
            kind="skywalking", files=(str(out_path),),
            n_records=doc["metadata"]["span_count"], n_skipped=empty,
            notes=(f"{len(pairs)} traces ({empty} empty-span summaries "
                   f"skipped)",))


# ---------------------------------------------------------------------------
# Elasticsearch (sw_segment-*)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ElasticsearchClient:
    """Raw segment-index client (TT enhanced trace path): time-windowed
    ``sw_segment-*`` search, segment records in the ``detailed_traces``
    schema ``anomod.io.tt_traces_es`` loads (service ids stay base64 —
    the LOADER owns decoding, one definition)."""
    base_url: str
    transport: HttpTransport = dataclasses.field(default_factory=HttpTransport)

    def segments(self, size: int = 1000, hours_back: float = 24.0,
                 now_s: Optional[float] = None) -> List[dict]:
        now = time.time() if now_s is None else now_s
        query = {
            "query": {"bool": {"must": [{"range": {"start_time": {
                "gte": int((now - hours_back * 3600.0) * 1000),
                "lte": int(now * 1000),
            }}}]}},
            "size": size,
            "sort": [{"start_time": {"order": "desc"}}],
        }
        doc = self.transport.request_json(
            f"{self.base_url}/sw_segment-*/_search", payload=query)
        hits = (doc or {}).get("hits", {}).get("hits", [])
        return [h.get("_source", {}) for h in hits]

    def collect(self, out_path: Path, size: int = 1000,
                hours_back: float = 24.0,
                now_s: Optional[float] = None) -> CollectReport:
        """Write the ``detailed_traces`` JSON artifact (records keep the
        raw ES fields: trace_id, segment_id, service_id, endpoint_name,
        start/end ms, latency, is_error)."""
        records = []
        for src in self.segments(size=size, hours_back=hours_back,
                                 now_s=now_s):
            records.append({
                "trace_id": src.get("trace_id", ""),
                "segment_id": src.get("segment_id", ""),
                "service_id": src.get("service_id", ""),
                "endpoint_name": src.get("endpoint_name", ""),
                "start_time": src.get("start_time", 0),
                "end_time": src.get("end_time", 0),
                "latency": src.get("latency", 0),
                "is_error": src.get("is_error", 0),
            })
        out_path = Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump({"metadata": {
                "hours_back": hours_back, "requested_size": size,
                "generator": "anomod.io.live.ElasticsearchClient",
            }, "traces": records}, f)
        return CollectReport(kind="elasticsearch",
                             files=(str(out_path),),
                             n_records=len(records))
