"""Metric CSV loaders → MetricBatch.

Two reference shapes:
  - SN per-query CSVs (one file per PromQL query, collect_metric.sh:24-125):
    columns ``timestamp,value,metric,<label cols>``
    (fetch_prometheus_metrics.py:57-66); timestamp is a wall-clock string.
  - TT single long CSV (metric_collector.py:431-443): columns
    ``metric_name,timestamp,datetime,value,<label cols>``; timestamp is epoch
    seconds.
"""

from __future__ import annotations

import csv
from datetime import datetime
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod.io.lfs import is_lfs_pointer
from anomod.schemas import MetricBatch

#: Ingest-cache key component (anomod.io.cache): bump when this module's
#: parsing semantics change, invalidating exactly the metric entries.
LOADER_VERSION = 1

_SERVICE_LABELS = ("service", "name", "pod", "container", "app")


def _parse_ts(s: str) -> float:
    try:
        return float(s)
    except ValueError:
        pass
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%dT%H:%M:%S"):
        try:
            return datetime.strptime(s.split(".")[0], fmt).timestamp()
        except ValueError:
            continue
    return 0.0


def _service_of(labels: Dict[str, str], services: Dict[str, int]) -> int:
    for key in _SERVICE_LABELS:
        v = labels.get(key, "")
        if v:
            # normalize pod name -> service name (strip replicaset hash)
            parts = v.split("-")
            while parts and (parts[-1].isalnum() and len(parts[-1]) in (5, 9, 10)
                             and any(c.isdigit() for c in parts[-1])):
                parts = parts[:-1]
            name = "-".join(parts) if parts else v
            return services.setdefault(name, len(services))
    return -1


def _build(rows: List[Tuple[str, float, float, Dict[str, str]]]) -> MetricBatch:
    metric_names: Dict[str, int] = {}
    series_keys: Dict[str, int] = {}
    services: Dict[str, int] = {}
    series_service: List[int] = []
    n = len(rows)
    metric_c = np.zeros(n, np.int32)
    series_c = np.zeros(n, np.int32)
    t_c = np.zeros(n, np.float64)
    v_c = np.zeros(n, np.float64)
    for i, (mname, ts, val, labels) in enumerate(rows):
        metric_c[i] = metric_names.setdefault(mname, len(metric_names))
        key = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        if key not in series_keys:
            series_keys[key] = len(series_keys)
            series_service.append(_service_of(labels, services))
        series_c[i] = series_keys[key]
        t_c[i] = ts
        v_c[i] = val
    return MetricBatch(
        metric=metric_c, series=series_c, t_s=t_c, value=v_c,
        metric_names=tuple(metric_names), series_keys=tuple(series_keys),
        series_service=np.array(series_service or [0], np.int32)[:len(series_keys)],
        services=tuple(services),
    )


def load_sn_metric_dir(exp_dir: Path) -> Optional[MetricBatch]:
    """Load every per-query CSV in an SN metric experiment dir."""
    exp_dir = Path(exp_dir)
    rows: List[Tuple[str, float, float, Dict[str, str]]] = []
    found = False
    for p in sorted(exp_dir.glob("*.csv")):
        if is_lfs_pointer(p):
            continue
        metric_name = p.stem
        with open(p, newline="") as f:
            for rec in csv.DictReader(f):
                if "value" not in rec or "timestamp" not in rec:
                    break
                found = True
                labels = {k: v for k, v in rec.items()
                          if k not in ("timestamp", "value", "metric") and v}
                try:
                    val = float(rec["value"])
                except (TypeError, ValueError):
                    val = float("nan")
                rows.append((metric_name, _parse_ts(rec["timestamp"]), val, labels))
    return _build(rows) if found else None


def load_tt_metric_csv(path: Path) -> Optional[MetricBatch]:
    """Load the TT long-format experiment CSV.

    Numeric fast path: when the header is the canonical TT layout
    (metric_name,timestamp,datetime,value,...; metric_collector.py:431-443)
    and the native library is built, the timestamp/value columns are parsed
    by the C++ CSV scanner; Python keeps the string columns."""
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    num = None
    raw = path.read_bytes()
    header = raw.split(b"\n", 1)[0].decode(errors="replace").strip().split(",")
    if header[:4] == ["metric_name", "timestamp", "datetime", "value"]:
        from anomod.io import native
        if native.enabled():
            num = native.scan_csv_columns(raw, [1, 3])
    # Validate the fast path before trusting it: the C++ scanner is
    # line-based, so quoted fields with embedded newlines (or whitespace-only
    # lines) desynchronize its row index from the csv module's record index —
    # require exact record-count agreement (streaming csv.reader pass, no
    # materialized row list) plus a first-record value/timestamp spot-check,
    # else fall back to pure Python for the whole file.
    if num is not None:
        with open(path, newline="") as f:
            n_rec = sum(1 for r in csv.reader(f) if r) - 1  # minus header
        if num.shape[1] != n_rec:
            num = None
    if num is not None and num.shape[1] > 0:
        with open(path, newline="") as f:
            first = next(csv.DictReader(f), None)
        if first is not None:
            py_t = _parse_ts(first.get("timestamp", "0"))
            try:
                py_v = float(first["value"]) if first.get("value") \
                    else float("nan")
            except (TypeError, ValueError):
                py_v = float("nan")
            nat_t = float(num[0, 0])
            nat_t = 0.0 if np.isnan(nat_t) else nat_t
            nat_v = float(num[1, 0])
            if nat_t != py_t or not (nat_v == py_v
                                     or (np.isnan(nat_v) and np.isnan(py_v))):
                num = None
    rows: List[Tuple[str, float, float, Dict[str, str]]] = []
    with open(path, newline="") as f:
        for i, rec in enumerate(csv.DictReader(f)):
            labels = {k: v for k, v in rec.items()
                      if k not in ("metric_name", "timestamp", "datetime", "value") and v}
            if num is not None and i < num.shape[1]:
                t = float(num[0, i])
                t = 0.0 if np.isnan(t) else t
                val = float(num[1, i])
            else:
                try:
                    val = float(rec["value"]) if rec.get("value") else float("nan")
                except (TypeError, ValueError):
                    val = float("nan")
                t = _parse_ts(rec.get("timestamp", "0"))
            rows.append((rec.get("metric_name", ""), t, val, labels))
    return _build(rows) if rows else None


def find_tt_metric_artifact(exp_dir: Path) -> Optional[Path]:
    cands = sorted(Path(exp_dir).glob("*_metrics_*.csv"))
    return cands[-1] if cands else None


def write_metric_batch_tt_csv(batch: MetricBatch, path: Path) -> None:
    """Materialize a MetricBatch in the TT long-CSV shape (for synth trees)."""
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["metric_name", "timestamp", "datetime", "value", "labels"])
        for i in range(batch.n_samples):
            ts = batch.t_s[i]
            w.writerow([
                batch.metric_names[int(batch.metric[i])], ts,
                datetime.fromtimestamp(ts).isoformat(),
                batch.value[i], batch.series_keys[int(batch.series[i])],
            ])
