"""ctypes bindings for the C++ native runtime (native/libanomod_native.so).

Builds on first use if the shared object is missing (g++ is baked into the
image); every entry point has a pure-Python fallback so the package works
without a toolchain.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libanomod_native.so"
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if not _SO_PATH.exists() and (_NATIVE_DIR / "Makefile").exists():
        try:
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True, timeout=120)
        except (subprocess.SubprocessError, OSError):
            return None
    if not _SO_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_SO_PATH))
    except OSError:
        return None
    lib.anomod_scan_log_mt.restype = ctypes.c_int64
    lib.anomod_scan_log_mt.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64, ctypes.c_int32]
    lib.anomod_scan_api_jsonl.restype = ctypes.c_int64
    lib.anomod_scan_api_jsonl.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    _LIB = lib
    return _LIB


def available() -> bool:
    return _load() is not None


def scan_log(text: bytes, n_threads: int = 4) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(levels int8, timestamps float64) per line; None if native unavailable."""
    lib = _load()
    if lib is None:
        return None
    max_lines = text.count(b"\n") + 1
    levels = np.empty(max_lines, np.int8)
    ts = np.empty(max_lines, np.float64)
    n = lib.anomod_scan_log_mt(
        text, len(text),
        levels.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        max_lines, n_threads)
    return levels[:n], ts[:n]


def scan_api_jsonl(text: bytes) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """(status int16, latency_ms float32, content_length int32) per record."""
    lib = _load()
    if lib is None:
        return None
    max_recs = text.count(b"\n") + 1
    status = np.empty(max_recs, np.int16)
    lat = np.empty(max_recs, np.float32)
    clen = np.empty(max_recs, np.int32)
    n = lib.anomod_scan_api_jsonl(
        text, len(text),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        lat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        clen.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        max_recs)
    return status[:n], lat[:n], clen[:n]
