"""ctypes bindings for the C++ native runtime (native/libanomod_native.so).

Builds on first use if the shared object is missing (g++ is baked into the
image); every entry point has a pure-Python fallback so the package works
without a toolchain.
"""

from __future__ import annotations

import ctypes
import subprocess
import warnings
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libanomod_native.so"
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
#: why the native runtime is unusable (build/load/symbol failure detail),
#: None while it is fine — surfaced by :func:`status` into
#: ``anomod validate`` and the serve pre-bench gate, and quoted by the
#: ANOMOD_NATIVE=on refusal so the operator sees the root cause instead
#: of a silent slow path
_BUILD_ERROR: Optional[str] = None


def _stale() -> bool:
    """True when the .so is missing or older than any native source."""
    if not _SO_PATH.exists():
        return True
    so_mtime = _SO_PATH.stat().st_mtime
    srcs = [_NATIVE_DIR / "Makefile", *_NATIVE_DIR.glob("*.cpp"),
            *_NATIVE_DIR.glob("*.h")]
    return any(s.exists() and s.stat().st_mtime > so_mtime for s in srcs)


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED, _BUILD_ERROR
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    # Only shell out to make when the .so is actually stale (mtime check):
    # read-only installs and toolchain-free hosts then skip the subprocess
    # spawn entirely, and a failed build degrades observably, not silently.
    if (_NATIVE_DIR / "Makefile").exists() and _stale():
        try:
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True, timeout=120)
        except (subprocess.SubprocessError, OSError) as e:
            detail = ""
            if isinstance(e, subprocess.CalledProcessError) and e.stderr:
                detail = ": " + e.stderr.decode(errors="replace")[-200:]
            _BUILD_ERROR = f"build failed ({type(e).__name__}{detail})"
            warnings.warn(
                f"anomod native build failed ({type(e).__name__}{detail}); "
                "falling back to stale .so or pure Python",
                RuntimeWarning, stacklevel=2)
    if not _SO_PATH.exists():
        if _BUILD_ERROR is None:
            _BUILD_ERROR = f"{_SO_PATH} missing and no build attempted " \
                           "(no Makefile or not stale)"
        return None
    try:
        lib = ctypes.CDLL(str(_SO_PATH))
    except OSError as e:
        _BUILD_ERROR = f"dlopen failed: {e}"
        return None
    try:
        _bind(lib)
    except AttributeError as e:
        # symbols missing (e.g. make failed against a stale .so): degrade to
        # the pure-Python fallbacks rather than raising from available()
        _BUILD_ERROR = f"stale .so missing symbols: {e}"
        return None
    _LIB = lib
    _BUILD_ERROR = None
    return _LIB


def _bind(lib: ctypes.CDLL) -> None:
    lib.anomod_scan_log_mt.restype = ctypes.c_int64
    lib.anomod_scan_log_mt.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64, ctypes.c_int32]
    lib.anomod_scan_api_jsonl.restype = ctypes.c_int64
    lib.anomod_scan_api_jsonl.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.anomod_rt_create.restype = ctypes.c_void_p
    lib.anomod_rt_create.argtypes = [ctypes.c_int32]
    lib.anomod_rt_destroy.restype = None
    lib.anomod_rt_destroy.argtypes = [ctypes.c_void_p]
    lib.anomod_rt_n_threads.restype = ctypes.c_int32
    lib.anomod_rt_n_threads.argtypes = [ctypes.c_void_p]
    lib.anomod_rt_summarize_logs.restype = ctypes.c_int64
    lib.anomod_rt_summarize_logs.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double)]
    lib.anomod_scan_csv_cols.restype = ctypes.c_int64
    lib.anomod_scan_csv_cols.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
    lib.anomod_stage_lanes.restype = ctypes.c_int64
    lib.anomod_stage_lanes.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int64]
    lib.anomod_stage_lanes_mat.restype = ctypes.c_int64
    lib.anomod_stage_lanes_mat.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64]
    lib.anomod_sfq_drain.restype = ctypes.c_int64
    lib.anomod_sfq_drain.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64, ctypes.c_double, ctypes.POINTER(ctypes.c_int64)]
    lib.anomod_sfq_victim.restype = ctypes.c_int64
    lib.anomod_sfq_victim.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64]


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    """Why the native runtime is unusable (None while it is fine)."""
    _load()
    return _BUILD_ERROR


def mode() -> str:
    """The validated ANOMOD_NATIVE knob value: auto | on | off."""
    from anomod.config import get_config
    return get_config().native


def enabled() -> bool:
    """The ONE gate every native consumer dispatches through (the ingest
    scanners and the serve staging alike): honors the validated
    ``ANOMOD_NATIVE`` knob on top of :func:`available` — ``off`` forces
    the pure-Python paths, ``on`` REQUIRES the runtime (raising with the
    recorded build-failure reason rather than silently degrading), and
    ``auto`` (default) uses it iff it loads."""
    m = mode()
    if m == "off":
        return False
    ok = available()
    if m == "on" and not ok:
        raise RuntimeError(
            "ANOMOD_NATIVE=on but the native runtime is unusable: "
            f"{_BUILD_ERROR or 'unknown load failure'} — rebuild with "
            "`make -C native smoke` or unset ANOMOD_NATIVE to accept the "
            "pure-Python fallback")
    return ok


def staging_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the serve staging path's native switch: an explicit
    ``override`` (the bench's python-staging reference leg passes False;
    True demands the runtime like ``ANOMOD_NATIVE=on``) beats the env
    knob; ``None`` defers to :func:`enabled`."""
    if override is None:
        return enabled()
    if not override:
        return False
    if not available():
        raise RuntimeError(
            "native staging requested but the runtime is unusable: "
            f"{_BUILD_ERROR or 'unknown load failure'}")
    return True


def sfq_kernels(require: bool = False):
    """The admission plane's columnar SFQ drain/shed kernels
    (``anomod_sfq_drain`` / ``anomod_sfq_victim``): the bound library
    handle, or None when the columnar engine should fall back to its
    pure-NumPy scans.

    ``require=True`` is the ``ANOMOD_SERVE_NATIVE_DRAIN=on`` contract —
    raise with the recorded build-failure reason instead of silently
    serving the fallback (the ``staging_enabled(override=True)``
    discipline); ``require=False`` defers to :func:`enabled`, so
    ``ANOMOD_NATIVE=off`` forces the NumPy scans like every other
    native consumer."""
    if require:
        if not available():
            raise RuntimeError(
                "ANOMOD_SERVE_NATIVE_DRAIN=on but the native runtime is "
                f"unusable: {_BUILD_ERROR or 'unknown load failure'} — "
                "rebuild with `make -C native` or set "
                "ANOMOD_SERVE_NATIVE_DRAIN=auto to accept the NumPy "
                "fallback")
        return _LIB
    return _LIB if enabled() else None


def status() -> dict:
    """The native runtime's health document (JSON-able): knob value,
    availability, .so path and the build-failure reason when unusable —
    surfaced by ``anomod validate`` and the serve pre-bench gate."""
    ok = available()
    m = mode()
    out = {
        "mode": m,
        "available": ok,
        "so_path": str(_SO_PATH) if _SO_PATH.exists() else None,
        "build_error": _BUILD_ERROR,
        "staging": bool(ok and m != "off"),
    }
    if m == "on" and not ok:
        out["error"] = ("ANOMOD_NATIVE=on but the native runtime is "
                        "unusable — see build_error")
    return out


def scan_log(text: bytes, n_threads: int = 4) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(levels int8, timestamps float64) per line; None if native unavailable."""
    lib = _load()
    if lib is None:
        return None
    max_lines = text.count(b"\n") + 1
    levels = np.empty(max_lines, np.int8)
    ts = np.empty(max_lines, np.float64)
    n = lib.anomod_scan_log_mt(
        text, len(text),
        levels.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        max_lines, n_threads)
    return levels[:n], ts[:n]


class Runtime:
    """Persistent native thread-pool executor (anomod_rt_* ABI).

    One pool serves many batch submissions; per-thread read buffers are
    reused across files.  Use as a context manager, or rely on
    :func:`default_runtime` for a process-wide singleton.
    """

    def __init__(self, n_threads: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._ptr = lib.anomod_rt_create(n_threads)

    @property
    def n_threads(self) -> int:
        return int(self._lib.anomod_rt_n_threads(self._ptr))

    def close(self) -> None:
        if self._ptr:
            self._lib.anomod_rt_destroy(self._ptr)
            self._ptr = None

    def __enter__(self) -> "Runtime":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def summarize_logs(self, paths) -> Tuple[np.ndarray, np.ndarray, int]:
        """Parallel per-file log summary sweep.

        Returns ``(counts [N,5] int64, ts [N,2] float64, n_readable)`` where
        counts rows are {n_lines, n_info, n_warn, n_error, size_bytes} and
        ts rows are {min_ts, max_ts} (0 when absent).
        """
        enc = [str(p).encode() for p in paths]
        arr = (ctypes.c_char_p * len(enc))(*enc)
        counts = np.zeros((len(enc), 5), np.int64)
        ts = np.zeros((len(enc), 2), np.float64)
        n = self._lib.anomod_rt_summarize_logs(
            self._ptr, arr, len(enc),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return counts, ts, int(n)


_DEFAULT_RT: Optional[Runtime] = None


def default_runtime() -> Optional[Runtime]:
    """Process-wide executor (4 workers), created lazily; None if no lib."""
    global _DEFAULT_RT
    if _DEFAULT_RT is None and _load() is not None:
        import atexit
        _DEFAULT_RT = Runtime(4)
        atexit.register(_DEFAULT_RT.close)
    return _DEFAULT_RT


def summarize_log_files(paths) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(counts [N,5], ts [N,2]) via the default runtime; None if native
    unavailable.  Unreadable files yield all-zero rows."""
    rt = default_runtime()
    if rt is None or not paths:
        return None
    counts, ts, _ = rt.summarize_logs(paths)
    return counts, ts


def scan_csv_columns(text: bytes, cols,
                     skip_header: bool = True) -> Optional[np.ndarray]:
    """Parse numeric CSV columns natively: [n_cols, n_rows] float64 with NaN
    for non-numeric fields.  None if native unavailable."""
    lib = _load()
    if lib is None:
        return None
    max_rows = text.count(b"\n") + 1
    cols_arr = np.asarray(list(cols), np.int32)
    out = np.empty((len(cols_arr), max_rows), np.float64)
    n = lib.anomod_scan_csv_cols(
        text, len(text),
        cols_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(cols_arr), int(skip_header),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), max_rows)
    return out[:, :n]


def aligned_empty(shape, dtype, align: int = 64) -> np.ndarray:
    """An uninitialized C-contiguous array whose data pointer is
    ``align``-byte aligned.  The serve scratch ring allocates through this
    so XLA:CPU's zero-copy host-buffer aliasing applies to the pinned
    ``[lanes, width]`` slots the executables read — ``np.empty`` only
    guarantees 16-byte alignment, and an unaligned buffer silently costs
    a copy per dispatch."""
    dt = np.dtype(dtype)
    shape = tuple(int(s) for s in np.atleast_1d(shape)) \
        if not np.isscalar(shape) else (int(shape),)
    size = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    buf = np.empty(size + align, np.uint8)
    ofs = (-buf.ctypes.data) % align
    return buf[ofs:ofs + size].view(dt).reshape(shape)


class StagedChunk(dict):
    """One staged chunk's column views PLUS the matrix-carrier fields
    the native fast path reads: the chunk is ``mat[:, lo:lo+m]`` of a
    C-contiguous ``[n_cols, stride]`` float32 staging matrix
    (anomod.replay.stage_columns_fused), so a whole lane marshals as
    three ints — ``ptr`` (``mat`` data pointer + 4·lo, precomputed ONCE
    per staged batch, where a per-call ``.ctypes.data`` extraction costs
    as much as a small numpy copy on a slow host), ``stride`` (the
    matrix row length in elements) and ``m`` (live rows).  ``mat`` is
    held only to keep the pointer's backing memory alive.  Behaves as
    the plain column dict everywhere else — consumers that feed jit
    (pytree) must convert with ``dict(cols)``."""

    __slots__ = ("mat", "ptr", "stride", "m")


class StagePlan:
    """Per-scratch-slot marshalling cache for the GIL-free native pack.

    The pinned ``[lanes, width]`` scratch buffers live for the runner's
    lifetime, so everything about them — destination pointers, per-column
    fill patterns, dtype checks, the ctypes argument arrays — marshals
    ONCE here instead of per dispatch (the per-call ctypes setup is what
    made a naive wrapper slower than the interpreter fill it replaced).
    Per call only the live lanes' source descriptors are written:
    three ints per lane when the chunks are :class:`StagedChunk` matrix
    carriers (the serve path), or per-column pointer extraction as the
    general fallback for plain dicts.

    Built via :func:`make_stage_plan`; ``stage(group_cols)`` returns
    False (caller runs the interpreter fill) on any contract break —
    never stages garbage bytes.
    """

    __slots__ = ("_lib", "_rt_ptr", "_keys", "_dtypes", "_n_cols",
                 "_lanes", "_width", "_expect", "_dst", "_fills", "_rows",
                 "_bases", "_strides", "_src", "_mat_ok")

    def __init__(self, lib, scratch, fill_for, mat_keys=None):
        keys = list(scratch)
        first = scratch[keys[0]]
        if first.ndim != 2:
            raise ValueError("scratch buffers must be [lanes, width]")
        lanes, width = map(int, first.shape)
        n_cols = len(keys)
        self._dst = (ctypes.c_void_p * n_cols)()
        self._fills = (ctypes.c_uint32 * n_cols)()
        dtypes = []
        for c, k in enumerate(keys):
            buf = scratch[k]
            if (buf.shape != (lanes, width) or buf.dtype.itemsize != 4
                    or not buf.flags.c_contiguous):
                raise ValueError(f"scratch[{k!r}] breaks the 4-byte "
                                 "C-contiguous [lanes, width] contract")
            self._dst[c] = buf.ctypes.data
            self._fills[c] = int(np.array([fill_for(k)],
                                          dtype=buf.dtype).view(np.uint32)[0])
            dtypes.append(buf.dtype)
        self._lib = lib
        rt = default_runtime()
        self._rt_ptr = rt._ptr if rt is not None else None
        self._keys = keys
        self._dtypes = dtypes
        self._n_cols = n_cols
        self._lanes = lanes
        self._width = width
        self._expect = n_cols * lanes * width
        self._rows = (ctypes.c_int64 * lanes)()
        self._bases = (ctypes.c_void_p * lanes)()
        self._strides = (ctypes.c_int64 * lanes)()
        self._src = None                     # lazily, general path only
        #: matrix fast path is sound only when the scratch columns are
        #: exactly the staged matrix's rows, in row order
        self._mat_ok = (mat_keys is not None
                        and keys == list(mat_keys))

    def stage(self, group_cols) -> bool:
        """Pack ``group_cols`` (one unpadded chunk per live lane) into
        the planned scratch slot, dead-filling row tails and dead lanes
        — byte-identical to the interpreter fill, GIL released for the
        whole native call."""
        n_live = len(group_cols)
        if n_live > self._lanes:
            return False
        if self._mat_ok:
            try:
                rows, bases, strides = self._rows, self._bases, \
                    self._strides
                width = self._width
                for i, cols in enumerate(group_cols):
                    m = cols.m
                    if m > width or cols.mat.shape[0] != self._n_cols:
                        return False
                    rows[i] = m
                    bases[i] = cols.ptr
                    strides[i] = cols.stride
            except AttributeError:
                pass                         # plain dicts: general path
            else:
                n = self._lib.anomod_stage_lanes_mat(
                    self._rt_ptr, self._dst, bases, strides, rows,
                    self._fills, self._n_cols, n_live, self._lanes,
                    self._width)
                return n == self._expect
        return self._stage_ptrs(group_cols, n_live)

    def _stage_ptrs(self, group_cols, n_live: int) -> bool:
        """The general path: per-column pointer extraction from plain
        column dicts (arbitrary 1-D 4-byte arrays), with the full
        dtype/contiguity contract checked per column."""
        if self._src is None:
            self._src = (ctypes.c_void_p * (self._n_cols * self._lanes))()
        src, rows, width = self._src, self._rows, self._width
        k0 = self._keys[0]
        for i, cols in enumerate(group_cols):
            m = cols[k0].shape[0]
            if m > width:
                return False
            rows[i] = m
        for c, k in enumerate(self._keys):
            want = self._dtypes[c]
            base = c * n_live
            for i, cols in enumerate(group_cols):
                col = cols[k]
                if (col.dtype != want or col.ndim != 1
                        or col.shape[0] != rows[i]
                        or not col.flags.c_contiguous):
                    return False
                src[base + i] = col.ctypes.data
        n = self._lib.anomod_stage_lanes(
            self._rt_ptr, self._dst, src, rows, self._fills,
            self._n_cols, n_live, self._lanes, self._width)
        return n == self._expect


def make_stage_plan(scratch, fill_for,
                    mat_keys=None) -> Optional[StagePlan]:
    """A :class:`StagePlan` for the pinned ``scratch`` slot, or None when
    the native runtime is unavailable or the slot breaks the 4-byte
    C-contiguous contract (caller keeps the interpreter fill).
    ``mat_keys`` (the staged-matrix row order, anomod.replay.STAGE_KEYS)
    enables the matrix fast path when the scratch keys match it."""
    lib = _load()
    if lib is None or not scratch:
        return None
    try:
        return StagePlan(lib, scratch, fill_for, mat_keys=mat_keys)
    except ValueError:
        return None


def stage_lanes(scratch, group_cols, fill_for) -> bool:
    """Pack one fused dispatch's lane scratch NATIVELY, GIL-free.

    ``scratch`` maps column name -> the pinned ``[lanes, width]`` buffer,
    ``group_cols`` is the ordered list of live lanes' unpadded column
    dicts, ``fill_for(key)`` the per-column dead-row fill scalar.  The
    result is byte-identical to the interpreter fill
    (``buf[i, :m] = col; buf[i, m:] = fill; buf[n_live:] = fill`` per
    column) — every chunk column is a 4-byte dtype, so the native copy is
    dtype-blind memcpy + pattern fill.  Returns False (caller falls back
    to the Python fill) when the runtime is unavailable or any array
    breaks the 4-byte / C-contiguous / dtype-match contract.

    The ctypes call releases the GIL for its whole duration, and large
    slots fan the per-column fills across the persistent native thread
    pool (:func:`default_runtime`) — staging for scratch slot k+1 can
    make progress under the in-flight dispatch on slot k, and shard
    workers stage concurrently instead of convoying on the interpreter
    lock (the GIL-overlap smoke in tests/test_native.py pins this).

    One-shot convenience over :func:`make_stage_plan` — the serve hot
    loop caches a :class:`StagePlan` per pinned slot instead, so the
    per-call marshalling cost here (pointer extraction per column) is
    paid once per slot, not per dispatch.
    """
    plan = make_stage_plan(scratch, fill_for)
    return plan is not None and plan.stage(group_cols)


def scan_api_jsonl(text: bytes) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """(status int16, latency_ms float32, content_length int32) per record."""
    lib = _load()
    if lib is None:
        return None
    max_recs = text.count(b"\n") + 1
    status = np.empty(max_recs, np.int16)
    lat = np.empty(max_recs, np.float32)
    clen = np.empty(max_recs, np.int32)
    n = lib.anomod_scan_api_jsonl(
        text, len(text),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        lat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        clen.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        max_recs)
    return status[:n], lat[:n], clen[:n]
