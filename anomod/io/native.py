"""ctypes bindings for the C++ native runtime (native/libanomod_native.so).

Builds on first use if the shared object is missing (g++ is baked into the
image); every entry point has a pure-Python fallback so the package works
without a toolchain.
"""

from __future__ import annotations

import ctypes
import subprocess
import warnings
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libanomod_native.so"
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _stale() -> bool:
    """True when the .so is missing or older than any native source."""
    if not _SO_PATH.exists():
        return True
    so_mtime = _SO_PATH.stat().st_mtime
    srcs = [_NATIVE_DIR / "Makefile", *_NATIVE_DIR.glob("*.cpp"),
            *_NATIVE_DIR.glob("*.h")]
    return any(s.exists() and s.stat().st_mtime > so_mtime for s in srcs)


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    # Only shell out to make when the .so is actually stale (mtime check):
    # read-only installs and toolchain-free hosts then skip the subprocess
    # spawn entirely, and a failed build degrades observably, not silently.
    if (_NATIVE_DIR / "Makefile").exists() and _stale():
        try:
            subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                           capture_output=True, timeout=120)
        except (subprocess.SubprocessError, OSError) as e:
            detail = ""
            if isinstance(e, subprocess.CalledProcessError) and e.stderr:
                detail = ": " + e.stderr.decode(errors="replace")[-200:]
            warnings.warn(
                f"anomod native build failed ({type(e).__name__}{detail}); "
                "falling back to stale .so or pure Python",
                RuntimeWarning, stacklevel=2)
    if not _SO_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_SO_PATH))
    except OSError:
        return None
    try:
        _bind(lib)
    except AttributeError:
        # symbols missing (e.g. make failed against a stale .so): degrade to
        # the pure-Python fallbacks rather than raising from available()
        return None
    _LIB = lib
    return _LIB


def _bind(lib: ctypes.CDLL) -> None:
    lib.anomod_scan_log_mt.restype = ctypes.c_int64
    lib.anomod_scan_log_mt.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64, ctypes.c_int32]
    lib.anomod_scan_api_jsonl.restype = ctypes.c_int64
    lib.anomod_scan_api_jsonl.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.anomod_rt_create.restype = ctypes.c_void_p
    lib.anomod_rt_create.argtypes = [ctypes.c_int32]
    lib.anomod_rt_destroy.restype = None
    lib.anomod_rt_destroy.argtypes = [ctypes.c_void_p]
    lib.anomod_rt_n_threads.restype = ctypes.c_int32
    lib.anomod_rt_n_threads.argtypes = [ctypes.c_void_p]
    lib.anomod_rt_summarize_logs.restype = ctypes.c_int64
    lib.anomod_rt_summarize_logs.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double)]
    lib.anomod_scan_csv_cols.restype = ctypes.c_int64
    lib.anomod_scan_csv_cols.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64]


def available() -> bool:
    return _load() is not None


def scan_log(text: bytes, n_threads: int = 4) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(levels int8, timestamps float64) per line; None if native unavailable."""
    lib = _load()
    if lib is None:
        return None
    max_lines = text.count(b"\n") + 1
    levels = np.empty(max_lines, np.int8)
    ts = np.empty(max_lines, np.float64)
    n = lib.anomod_scan_log_mt(
        text, len(text),
        levels.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        max_lines, n_threads)
    return levels[:n], ts[:n]


class Runtime:
    """Persistent native thread-pool executor (anomod_rt_* ABI).

    One pool serves many batch submissions; per-thread read buffers are
    reused across files.  Use as a context manager, or rely on
    :func:`default_runtime` for a process-wide singleton.
    """

    def __init__(self, n_threads: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._ptr = lib.anomod_rt_create(n_threads)

    @property
    def n_threads(self) -> int:
        return int(self._lib.anomod_rt_n_threads(self._ptr))

    def close(self) -> None:
        if self._ptr:
            self._lib.anomod_rt_destroy(self._ptr)
            self._ptr = None

    def __enter__(self) -> "Runtime":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def summarize_logs(self, paths) -> Tuple[np.ndarray, np.ndarray, int]:
        """Parallel per-file log summary sweep.

        Returns ``(counts [N,5] int64, ts [N,2] float64, n_readable)`` where
        counts rows are {n_lines, n_info, n_warn, n_error, size_bytes} and
        ts rows are {min_ts, max_ts} (0 when absent).
        """
        enc = [str(p).encode() for p in paths]
        arr = (ctypes.c_char_p * len(enc))(*enc)
        counts = np.zeros((len(enc), 5), np.int64)
        ts = np.zeros((len(enc), 2), np.float64)
        n = self._lib.anomod_rt_summarize_logs(
            self._ptr, arr, len(enc),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return counts, ts, int(n)


_DEFAULT_RT: Optional[Runtime] = None


def default_runtime() -> Optional[Runtime]:
    """Process-wide executor (4 workers), created lazily; None if no lib."""
    global _DEFAULT_RT
    if _DEFAULT_RT is None and _load() is not None:
        import atexit
        _DEFAULT_RT = Runtime(4)
        atexit.register(_DEFAULT_RT.close)
    return _DEFAULT_RT


def summarize_log_files(paths) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(counts [N,5], ts [N,2]) via the default runtime; None if native
    unavailable.  Unreadable files yield all-zero rows."""
    rt = default_runtime()
    if rt is None or not paths:
        return None
    counts, ts, _ = rt.summarize_logs(paths)
    return counts, ts


def scan_csv_columns(text: bytes, cols,
                     skip_header: bool = True) -> Optional[np.ndarray]:
    """Parse numeric CSV columns natively: [n_cols, n_rows] float64 with NaN
    for non-numeric fields.  None if native unavailable."""
    lib = _load()
    if lib is None:
        return None
    max_rows = text.count(b"\n") + 1
    cols_arr = np.asarray(list(cols), np.int32)
    out = np.empty((len(cols_arr), max_rows), np.float64)
    n = lib.anomod_scan_csv_cols(
        text, len(text),
        cols_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(cols_arr), int(skip_header),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), max_rows)
    return out[:, :n]


def scan_api_jsonl(text: bytes) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """(status int16, latency_ms float32, content_length int32) per record."""
    lib = _load()
    if lib is None:
        return None
    max_recs = text.count(b"\n") + 1
    status = np.empty(max_recs, np.int16)
    lat = np.empty(max_recs, np.float32)
    clen = np.empty(max_recs, np.int32)
    n = lib.anomod_scan_api_jsonl(
        text, len(text),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        lat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        clen.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        max_recs)
    return status[:n], lat[:n], clen[:n]
