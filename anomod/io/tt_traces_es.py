"""TT enhanced (Elasticsearch) trace collector schema → SpanBatch.

The reference's alternative trace path queries SkyWalking's ``sw_segment-*``
indices directly and emits segment-level records
(enhanced_trace_collector.py:102-163: trace_id, segment_id, base64-encoded
``service_id``, endpoint_name, start/end ms, latency, is_error) as a
``detailed_traces_<ts>.{json,csv}`` pair (:168-213).  Segments carry no
parent refs in this export, so parents resolve to -1 (segment-level view).
"""

from __future__ import annotations

import base64
import csv
import json
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from anomod.io.lfs import is_lfs_pointer
from anomod.schemas import KIND_ENTRY, SpanBatch, empty_span_batch


def decode_service_id(service_id: str) -> str:
    """``dHMtdHJhdmVsLXNlcnZpY2U=.1`` -> ``ts-travel-service``
    (enhanced_trace_collector.py:131-148)."""
    if not service_id:
        return "unknown"
    b64 = service_id.split(".")[0]
    try:
        return base64.b64decode(b64, validate=True).decode("utf-8")
    except Exception:
        return b64


def _records_to_batch(records: List[dict]) -> SpanBatch:
    if not records:
        return empty_span_batch()
    n = len(records)
    services: Dict[str, int] = {}
    endpoints: Dict[str, int] = {}
    trace_ids: Dict[str, int] = {}
    trace_c = np.zeros(n, np.int32)
    service_c = np.zeros(n, np.int32)
    endpoint_c = np.zeros(n, np.int32)
    start_c = np.zeros(n, np.int64)
    dur_c = np.zeros(n, np.int64)
    err_c = np.zeros(n, np.bool_)
    for r, rec in enumerate(records):
        trace_c[r] = trace_ids.setdefault(str(rec.get("trace_id", "")), len(trace_ids))
        svc = rec.get("service_name") or decode_service_id(str(rec.get("service_id", "")))
        service_c[r] = services.setdefault(svc, len(services))
        endpoint_c[r] = endpoints.setdefault(str(rec.get("endpoint_name", "")),
                                             len(endpoints))
        start_ms = int(float(rec.get("start_time", 0) or 0))
        latency = rec.get("latency", 0)
        end_ms = int(float(rec.get("end_time", 0) or 0))
        start_c[r] = start_ms * 1000
        dur_c[r] = int(float(latency or 0)) * 1000 if latency else \
            max(0, end_ms - start_ms) * 1000
        err_c[r] = bool(int(float(rec.get("is_error", 0) or 0)))
    return SpanBatch(
        trace=trace_c, parent=np.full(n, -1, np.int32), service=service_c,
        endpoint=endpoint_c, start_us=start_c, duration_us=dur_c,
        is_error=err_c, status=np.zeros(n, np.int16),
        kind=np.full(n, KIND_ENTRY, np.int8),
        services=tuple(services), endpoints=tuple(endpoints),
        trace_ids=tuple(trace_ids),
    ).validate()


def load_detailed_traces_json(path: Path) -> Optional[SpanBatch]:
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    return _records_to_batch(doc.get("traces", []))


def load_detailed_traces_csv(path: Path) -> Optional[SpanBatch]:
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    with open(path, newline="") as f:
        return _records_to_batch(list(csv.DictReader(f)))
