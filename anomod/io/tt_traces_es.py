"""TT enhanced (Elasticsearch) trace collector schema → SpanBatch.

The reference's alternative trace path queries SkyWalking's ``sw_segment-*``
indices directly and emits segment-level records
(enhanced_trace_collector.py:102-163: trace_id, segment_id, base64-encoded
``service_id``, endpoint_name, start/end ms, latency, is_error) as a
``detailed_traces_<ts>.{json,csv}`` pair (:168-213).  Segments carry no
parent refs in this export, so parents resolve to -1 (segment-level view).
"""

from __future__ import annotations

import base64
import csv
import json
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from anomod.io.lfs import is_lfs_pointer
from anomod.schemas import KIND_ENTRY, SpanBatch, empty_span_batch


def decode_service_id(service_id: str) -> str:
    """``dHMtdHJhdmVsLXNlcnZpY2U=.1`` -> ``ts-travel-service``
    (enhanced_trace_collector.py:131-148)."""
    if not service_id:
        return "unknown"
    b64 = service_id.split(".")[0]
    try:
        return base64.b64decode(b64, validate=True).decode("utf-8")
    except Exception:
        return b64


def _records_to_batch(records: List[dict]) -> SpanBatch:
    if not records:
        return empty_span_batch()
    n = len(records)
    services: Dict[str, int] = {}
    endpoints: Dict[str, int] = {}
    trace_ids: Dict[str, int] = {}
    trace_c = np.zeros(n, np.int32)
    service_c = np.zeros(n, np.int32)
    endpoint_c = np.zeros(n, np.int32)
    start_c = np.zeros(n, np.int64)
    dur_c = np.zeros(n, np.int64)
    err_c = np.zeros(n, np.bool_)
    for r, rec in enumerate(records):
        trace_c[r] = trace_ids.setdefault(str(rec.get("trace_id", "")), len(trace_ids))
        svc = rec.get("service_name") or decode_service_id(str(rec.get("service_id", "")))
        service_c[r] = services.setdefault(svc, len(services))
        endpoint_c[r] = endpoints.setdefault(str(rec.get("endpoint_name", "")),
                                             len(endpoints))
        start_ms = int(float(rec.get("start_time", 0) or 0))
        latency = rec.get("latency", 0)
        end_ms = int(float(rec.get("end_time", 0) or 0))
        start_c[r] = start_ms * 1000
        dur_c[r] = int(float(latency or 0)) * 1000 if latency else \
            max(0, end_ms - start_ms) * 1000
        err_c[r] = bool(int(float(rec.get("is_error", 0) or 0)))
    return SpanBatch(
        trace=trace_c, parent=np.full(n, -1, np.int32), service=service_c,
        endpoint=endpoint_c, start_us=start_c, duration_us=dur_c,
        is_error=err_c, status=np.zeros(n, np.int16),
        kind=np.full(n, KIND_ENTRY, np.int8),
        services=tuple(services), endpoints=tuple(endpoints),
        trace_ids=tuple(trace_ids),
    ).validate()


def load_detailed_traces_json(path: Path) -> Optional[SpanBatch]:
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    return _records_to_batch(doc.get("traces", []))


def load_detailed_traces_csv(path: Path) -> Optional[SpanBatch]:
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    with open(path, newline="") as f:
        return _records_to_batch(list(csv.DictReader(f)))


def analyze_trace_patterns(batch: SpanBatch) -> dict:
    """Aggregate trace-pattern summary, schema-matched to the reference's
    ``analyze_trace_patterns`` (enhanced_trace_collector.py:216-296):
    total count, distinct services/endpoints, per-service and per-endpoint
    call counts, error-trace count, latency min/max/avg over positive
    latencies, and the [earliest, latest] start-time window with ISO
    datetime renderings.

    Computed vectorized over the SpanBatch columns (bincount + reductions)
    instead of the reference's per-record Python loop; latencies are
    reported in ms (the ES export's unit — the batch stores µs)."""
    import datetime

    if batch.n_spans == 0:
        return {
            "total_traces": 0,
            "unique_services": [],
            "unique_endpoints": [],
            "error_traces": 0,
            "service_call_counts": {},
            "endpoint_call_counts": {},
            "latency_stats": None,
            "time_range": {"earliest": None, "latest": None},
        }
    svc_counts = np.bincount(batch.service, minlength=len(batch.services))
    ep_counts = np.bincount(batch.endpoint, minlength=len(batch.endpoints))
    lat_ms = batch.duration_us.astype(np.float64) / 1000.0
    pos = lat_ms[lat_ms > 0]
    start_ms = batch.start_us.astype(np.int64) // 1000
    analysis = {
        "total_traces": int(batch.n_spans),
        "unique_services": list(batch.services),
        "unique_endpoints": list(batch.endpoints),
        "error_traces": int(batch.is_error.sum()),
        "service_call_counts": {s: int(c) for s, c
                                in zip(batch.services, svc_counts)},
        "endpoint_call_counts": {e: int(c) for e, c
                                 in zip(batch.endpoints, ep_counts)},
        "latency_stats": ({
            "min": float(pos.min()),
            "max": float(pos.max()),
            "avg": float(pos.mean()),
            "count": int(pos.size),
        } if pos.size else None),
        "time_range": {
            "earliest": int(start_ms.min()),
            "latest": int(start_ms.max()),
        },
    }
    # datetime renderings ride alongside the raw ms timestamps, added only
    # when truthy — the reference's exact conditional (:286-294).  Rendered
    # in UTC (naive format, like the reference's local-time strings) so the
    # artifact bytes don't depend on the host timezone.
    for key in ("earliest", "latest"):
        ms = analysis["time_range"][key]
        if ms:
            dt = datetime.datetime.fromtimestamp(
                ms / 1000, tz=datetime.timezone.utc).replace(tzinfo=None)
            analysis["time_range"][f"{key}_datetime"] = dt.isoformat()
    return analysis


def format_analysis_report(analysis: dict, hours_back: int = 24,
                           top_n: int = 10) -> str:
    """The human-readable analysis report the reference prints after a
    collect-and-analyze run (enhanced_trace_collector.py:326-357): header,
    totals, error rate, latency stats, and the top-N service/endpoint
    call-count rankings."""
    bar = "=" * 80
    lines = [bar, "Train-Ticket Trace Analysis Report", bar,
             f"Time window: last {hours_back} hours",
             f"Total traces: {analysis['total_traces']:,}",
             f"Distinct services: {len(analysis['unique_services'])}",
             f"Distinct endpoints: {len(analysis['unique_endpoints'])}",
             f"Error traces: {analysis['error_traces']}"]
    if analysis["total_traces"] > 0:
        rate = analysis["error_traces"] / analysis["total_traces"] * 100
        lines.append(f"Error rate: {rate:.2f}%")
    else:
        lines.append("Error rate: N/A (no traces collected)")
    if analysis["latency_stats"]:
        ls = analysis["latency_stats"]
        lines += ["", "Latency statistics:",
                  f"  Min latency: {ls['min']} ms",
                  f"  Max latency: {ls['max']} ms",
                  f"  Avg latency: {ls['avg']:.2f} ms"]
    for title, counts in (("services", analysis["service_call_counts"]),
                          ("endpoints", analysis["endpoint_call_counts"])):
        ranked = sorted(counts.items(), key=lambda kv: kv[1], reverse=True)
        lines += ["", f"Top {title} ({top_n}):"]
        lines += [f"  {i:2d}. {name}: {count:,} calls"
                  for i, (name, count) in enumerate(ranked[:top_n], 1)]
    lines.append(bar)
    return "\n".join(lines)


def write_trace_analysis(batch: SpanBatch, out_dir: Path,
                         timestamp: str = "00000000_000000") -> Path:
    """Materialize the ``trace_analysis_<ts>.json`` artifact
    (enhanced_trace_collector.py:316-323's envelope: timestamp,
    collection_time, analysis) plus the printed report as a sibling
    ``trace_analysis_<ts>.txt``.  ``timestamp`` is caller-supplied (the
    campaign's experiment clock) so artifacts are reproducible."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    analysis = analyze_trace_patterns(batch)
    path = out_dir / f"trace_analysis_{timestamp}.json"
    with open(path, "w") as f:
        json.dump({"timestamp": timestamp,
                   "collection_time": timestamp,
                   "analysis": analysis}, f, indent=2, ensure_ascii=False)
    (out_dir / f"trace_analysis_{timestamp}.txt").write_text(
        format_analysis_report(analysis) + "\n")
    return path


def load_trace_analysis(path: Path) -> Optional[dict]:
    """Load a ``trace_analysis_<ts>.json`` artifact; returns the envelope
    dict (or None for missing/LFS-stub files, like the other loaders)."""
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    with open(path) as f:
        return json.load(f)
