"""Content-addressed on-disk ingest cache for parsed modal batches.

Every parsed (or synth-generated) modality of an experiment is cached as a
columnar entry — one flat ``.npc`` payload (JSON header + raw C-order
column bytes; one open, one bulk read, zero-copy ``np.frombuffer`` column
views) plus a ``.json`` sidecar holding the key parts, versions, and the
recorded cold parse wall — so a warm ``load_corpus`` is a handful of
columnar reads instead of CSV/JSON/gcov parsing or synth regeneration.

Key contract (what addresses an entry):
  - ``CACHE_FORMAT_VERSION`` (this module's serialization layout),
  - the owning loader's ``LOADER_VERSION`` (per io module — bumping a
    loader invalidates exactly its modality) or ``synth.SYNTH_VERSION``
    for generator-produced fallbacks,
  - the modality kind + testbed + canonical experiment name,
  - for file-backed loads: the source fingerprint — sorted
    ``(relpath, size, mtime_ns)`` of every file under the modality dir,
    so any artifact change or addition invalidates the entry,
  - for synth fallbacks: ``n_traces`` (trace generator only) — every
    generator derives its seed from the label name, so label + version
    fully determines the output.

Crash/concurrency safety reuses the utils/checkpoint.py idiom: each file is
written to a same-directory temp name and atomically published with
``os.replace``, npz first and the json sidecar LAST — a reader that sees
the sidecar sees a complete entry, and a torn/corrupt entry is treated as a
miss (re-parse), never an error.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from anomod import obs
from anomod.schemas import (ApiBatch, CoverageBatch, LogBatch, LogSummary,
                            MetricBatch, SpanBatch)

#: Bump to invalidate every entry (serialization layout change).
CACHE_FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# Hit/miss accounting — surfaced by `anomod validate` / `anomod ingest`.
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    stores: int = 0
    errors: int = 0      # corrupt/torn entries dropped back to a re-parse

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_STATS = CacheStats()


def _count(event: str, n: int = 1) -> None:
    """Bump the process CacheStats counter AND its registry mirror —
    one call site per event, so the two views can never drift."""
    setattr(_STATS, event, getattr(_STATS, event) + n)
    obs.counter(f"anomod_ingest_cache_{event}_total").inc(n)


def stats() -> CacheStats:
    return _STATS


def reset_stats() -> None:
    global _STATS
    _STATS = CacheStats()


def merge_stats(other: dict) -> None:
    """Fold a worker process's counter snapshot into this process's stats
    (the spawn-pool loader's globals never propagate back on their own)."""
    for k, v in other.items():
        if hasattr(_STATS, k):
            _count(k, int(v))


# ---------------------------------------------------------------------------
# Keys and fingerprints
# ---------------------------------------------------------------------------

def cache_key(parts: Dict[str, Any]) -> str:
    """Content address: sha256 over the canonical JSON of the key parts."""
    blob = json.dumps(parts, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def full_key(kind: str, key_parts: Dict[str, Any]) -> str:
    """The ONE composition of caller key parts + kind + format version —
    shared by :func:`cached` and presence probes (the pre-bench gate), so
    the two can never desync on the key recipe."""
    return cache_key({**key_parts, "kind": kind,
                      "cache_format_version": CACHE_FORMAT_VERSION})


def dir_fingerprint(path: Path, max_files: int = 4096) -> List[Any]:
    """Sorted (relpath, size, mtime_ns) of every file under ``path``.

    The stat fingerprint is the cache's change detector: any edit, addition
    or removal of a source artifact changes the key.  Stat calls are
    bounded so a pathological tree cannot turn key computation into the
    slow path — but the TOTAL file count is always appended, so adding or
    removing files beyond the stat cap still changes the key instead of
    silently serving stale data.
    """
    path = Path(path)
    out: List[Any] = []
    n_files = 0
    try:
        for p in sorted(path.rglob("*")):
            if not p.is_file():
                continue
            n_files += 1
            if len(out) < max_files:
                st = p.stat()
                out.append([str(p.relative_to(path)), st.st_size,
                            st.st_mtime_ns])
    except OSError:
        pass
    out.append(["__n_files__", n_files])
    return out


def cache_root(cfg=None) -> Optional[Path]:
    """The configured cache directory, or None when caching is disabled."""
    if cfg is None:
        from anomod.config import get_config
        cfg = get_config()
    root = getattr(cfg, "cache_dir", None)
    return Path(root) if root else None


def entry_paths(root: Path, key: str) -> Tuple[Path, Path]:
    """(payload, json-sidecar) paths for a key, sharded by first hex byte."""
    d = Path(root) / key[:2]
    return d / f"{key}.npc", d / f"{key}.json"


# ---------------------------------------------------------------------------
# Per-kind encode/decode.  Arrays (including unicode string tables) go into
# the npz; only metadata lives in the sidecar.  ``None`` inside composite
# values (the logs (batch, summaries) pair) is encoded explicitly.
# ---------------------------------------------------------------------------

def _strs(values) -> np.ndarray:
    return np.asarray(list(values), dtype=np.str_)


def _encode(kind: str, value) -> Tuple[Dict[str, np.ndarray], dict]:
    if kind == "spans":
        b: SpanBatch = value
        arrays = {f: getattr(b, f) for f in
                  ("trace", "parent", "service", "endpoint", "start_us",
                   "duration_us", "is_error", "status", "kind")}
        arrays.update(tbl_services=_strs(b.services),
                      tbl_endpoints=_strs(b.endpoints),
                      tbl_trace_ids=_strs(b.trace_ids))
        return arrays, {}
    if kind == "metrics":
        m: MetricBatch = value
        arrays = {"metric": m.metric, "series": m.series, "t_s": m.t_s,
                  "value": m.value, "series_service": m.series_service,
                  "tbl_metric_names": _strs(m.metric_names),
                  "tbl_series_keys": _strs(m.series_keys),
                  "tbl_services": _strs(m.services)}
        return arrays, {}
    if kind == "logs":
        batch, summaries = value
        arrays: Dict[str, np.ndarray] = {}
        meta: dict = {"has_batch": batch is not None,
                      "summaries": None}
        if batch is not None:
            arrays = {"service": batch.service, "t_s": batch.t_s,
                      "level": batch.level,
                      "tbl_services": _strs(batch.services)}
        if summaries is not None:
            meta["summaries"] = [dataclasses.asdict(s) for s in summaries]
        return arrays, meta
    if kind == "api":
        a: ApiBatch = value
        arrays = {"endpoint": a.endpoint, "t_s": a.t_s, "status": a.status,
                  "latency_ms": a.latency_ms,
                  "content_length": a.content_length,
                  "tbl_endpoints": _strs(a.endpoints)}
        return arrays, {}
    if kind == "coverage":
        c: CoverageBatch = value
        arrays = {"service": c.service, "lines_total": c.lines_total,
                  "lines_covered": c.lines_covered,
                  "tbl_services": _strs(c.services),
                  "tbl_paths": _strs(c.paths)}
        return arrays, {}
    raise ValueError(f"unknown cache kind {kind!r}")


def _decode(kind: str, arrays: Dict[str, np.ndarray], meta: dict):
    def tbl(name):
        return tuple(arrays[name].tolist()) if name in arrays else ()
    if kind == "spans":
        return SpanBatch(
            trace=arrays["trace"], parent=arrays["parent"],
            service=arrays["service"], endpoint=arrays["endpoint"],
            start_us=arrays["start_us"], duration_us=arrays["duration_us"],
            is_error=arrays["is_error"], status=arrays["status"],
            kind=arrays["kind"],
            services=tbl("tbl_services"), endpoints=tbl("tbl_endpoints"),
            trace_ids=tbl("tbl_trace_ids"))
    if kind == "metrics":
        return MetricBatch(
            metric=arrays["metric"], series=arrays["series"],
            t_s=arrays["t_s"], value=arrays["value"],
            metric_names=tbl("tbl_metric_names"),
            series_keys=tbl("tbl_series_keys"),
            series_service=arrays["series_service"],
            services=tbl("tbl_services"))
    if kind == "logs":
        batch = None
        if meta.get("has_batch"):
            batch = LogBatch(service=arrays["service"], t_s=arrays["t_s"],
                             level=arrays["level"],
                             services=tbl("tbl_services"))
        summaries = meta.get("summaries")
        if summaries is not None:
            summaries = [LogSummary(**s) for s in summaries]
        return batch, summaries
    if kind == "api":
        return ApiBatch(
            endpoint=arrays["endpoint"], t_s=arrays["t_s"],
            status=arrays["status"], latency_ms=arrays["latency_ms"],
            content_length=arrays["content_length"],
            endpoints=tbl("tbl_endpoints"))
    if kind == "coverage":
        return CoverageBatch(
            service=arrays["service"], lines_total=arrays["lines_total"],
            lines_covered=arrays["lines_covered"],
            services=tbl("tbl_services"), paths=tbl("tbl_paths"))
    raise ValueError(f"unknown cache kind {kind!r}")


# ---------------------------------------------------------------------------
# Store / load with atomic publish.
#
# Payload layout (``.npc`` — "numpy columns"): the zip/CRC/per-array-header
# machinery of a real ``.npz`` costs milliseconds PER ENTRY on this class of
# filesystem (many tiny reads + ast-parsed headers), which would eat the
# warm-path win.  Instead: one flat file = magic + length-prefixed JSON
# header (entry meta + per-column dtype/shape/offset) + the raw C-order
# column bytes.  A warm read is ONE open + ONE bulk read; columns are
# zero-copy ``np.frombuffer`` views over the (writable) bytearray.
# ---------------------------------------------------------------------------

_MAGIC = b"ANOMODC1"


def _atomic_publish(path: Path, writer: Callable[[Any], None],
                    mode: str = "wb") -> None:
    tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
    with open(tmp, mode) as f:
        writer(f)
    os.replace(tmp, path)


def _write_payload(f, arrays: Dict[str, np.ndarray], meta: dict) -> None:
    cols = []
    offset = 0
    contig = {}
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        contig[name] = a
        cols.append({"name": name, "dtype": a.dtype.str,
                     "shape": list(a.shape), "offset": offset,
                     "nbytes": a.nbytes})
        offset += a.nbytes
    header = json.dumps({"meta": meta, "columns": cols},
                        sort_keys=True).encode()
    f.write(_MAGIC)
    f.write(len(header).to_bytes(8, "little"))
    f.write(header)
    for name in arrays:
        f.write(contig[name].tobytes())


def _read_payload(data: bytes):
    """(arrays, meta) from payload bytes; raises on any corruption."""
    if data[:len(_MAGIC)] != _MAGIC:
        raise ValueError("bad magic")
    n = int.from_bytes(data[len(_MAGIC):len(_MAGIC) + 8], "little")
    body_at = len(_MAGIC) + 8
    doc = json.loads(data[body_at:body_at + n].decode())
    base = body_at + n
    buf = memoryview(data)
    arrays: Dict[str, np.ndarray] = {}
    for col in doc["columns"]:
        lo = base + col["offset"]
        hi = lo + col["nbytes"]
        if hi > len(data):
            raise ValueError("truncated payload")
        arrays[col["name"]] = np.frombuffer(
            buf[lo:hi], dtype=np.dtype(col["dtype"])
        ).reshape(col["shape"])
    return arrays, doc["meta"]


def store(root: Path, key: str, kind: str, value,
          extra_meta: Optional[dict] = None) -> bool:
    """Publish an entry; returns False (never raises) on filesystem refusal."""
    payload_path, json_path = entry_paths(root, key)
    try:
        payload_path.parent.mkdir(parents=True, exist_ok=True)
        arrays, meta = _encode(kind, value)
        meta.update(extra_meta or {})
        meta.update(key=key, kind=kind,
                    cache_format_version=CACHE_FORMAT_VERSION)
        # payload first, sidecar last (checkpoint.py publish-order idiom);
        # both atomic, so a reader never sees a torn file — the sidecar is
        # the human-readable provenance view (key parts, parse wall) and
        # the pre-bench gate's presence marker, never the hot read path
        _atomic_publish(payload_path,
                        lambda f: _write_payload(f, arrays, meta))
        _atomic_publish(json_path,
                        lambda f: json.dump(meta, f, sort_keys=True),
                        mode="w")
        _count("stores")
        obs.counter("anomod_ingest_cache_written_bytes_total").inc(
            sum(int(a.nbytes) for a in arrays.values()))
        return True
    except OSError:
        return False


def load(root: Path, key: str, kind: str):
    """Return ``(value, meta)`` on a hit, None on miss/corrupt.

    A torn or corrupt entry (missing payload, truncated columns, wrong key
    in the header) counts as a miss — the caller re-parses and
    re-publishes.  Columns come back as writable views over one bytearray.
    """
    payload_path, _ = entry_paths(root, key)
    try:
        with open(payload_path, "rb") as f:
            data = bytearray(f.read())
    except OSError:
        return None
    obs.counter("anomod_ingest_cache_read_bytes_total").inc(len(data))
    try:
        arrays, meta = _read_payload(data)
        if (meta.get("key") != key or meta.get("kind") != kind
                or meta.get("cache_format_version") != CACHE_FORMAT_VERSION):
            _count("errors")
            return None
        return _decode(kind, arrays, meta), meta
    except Exception:
        _count("errors")
        return None


def cached(kind: str, key_parts: Dict[str, Any],
           compute: Callable[[], Any], cfg=None,
           cacheable: Callable[[Any], bool] = lambda v: v is not None):
    """The one read-through entry point: ``(value, hit, meta)``.

    On a miss, ``compute()`` runs and — when ``cacheable(value)`` — the
    result is published together with the measured cold parse wall
    (``meta["parse_s"]``), which warm hits then report back for honest
    cold-number accounting (bench.py's ``parse_s`` field).
    """
    root = cache_root(cfg)
    key = full_key(kind, key_parts)
    if root is not None:
        got = load(root, key, kind)
        if got is not None:
            _count("hits")
            return got[0], True, got[1]
        _count("misses")
    t0 = time.perf_counter()
    value = compute()
    parse_s = time.perf_counter() - t0
    meta = {"parse_s": parse_s}
    if root is not None and cacheable(value):
        store(root, key, kind, value, extra_meta=meta)
    return value, False, meta


def entry_count(root: Optional[Path]) -> int:
    """Number of published entries under a cache root (0 when disabled)."""
    if not root or not Path(root).is_dir():
        return 0
    return sum(1 for _ in Path(root).glob("*/*.json"))


def clear(root: Optional[Path]) -> int:
    """Delete every entry; returns the number of files removed."""
    if not root or not Path(root).is_dir():
        return 0
    n = 0
    for p in list(Path(root).glob("*/*")):
        try:
            p.unlink()
            n += 1
        except OSError:
            pass
    return n
