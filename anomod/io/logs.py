"""Log loaders → LogBatch / LogSummary.

SN layout: ``<exp>/<Service>_<ts>.log`` + ``summary.txt`` with per-service
line/error/warn counts (collect_log.sh:101-137; the shipped dataset's summary
uses an older localized format — parsed tolerantly by regex).

TT layout: ``<exp>/<pod>/<pod>_<ts>.log`` (+ ``_previous_``),
``kubernetes_events_*.json``, ``log_collection_report_*.json``
(log_collector.py:66-123,179-200).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from anomod.io.lfs import is_lfs_pointer, read_text_or_none
from anomod.schemas import (LOG_ERROR, LOG_INFO, LOG_OTHER, LOG_WARN, LogBatch,
                            LogSummary)

#: Ingest-cache key component (anomod.io.cache): bump when this module's
#: parsing semantics change, invalidating exactly the log entries.
LOADER_VERSION = 1

# "- ComposePostService: 124K (1001行) - 错误: 200, ..." or
# "- ComposePostService: 124K (1001 lines) | errors=200, warnings=0, ..."
_SUMMARY_LINE = re.compile(
    r"^-\s*(?P<svc>[\w.-]+):\s*(?P<size>[\d.]+[KMG]?)\s*\((?P<lines>\d+)")
_NUM = re.compile(r"(\d+)")

_SIZE_MULT = {"K": 1024, "M": 1024**2, "G": 1024**3}


def _parse_size(s: str) -> int:
    if s and s[-1] in _SIZE_MULT:
        return int(float(s[:-1]) * _SIZE_MULT[s[-1]])
    try:
        return int(float(s))
    except ValueError:
        return 0


def parse_sn_summary(text: str) -> List[LogSummary]:
    """Parse SN summary.txt (tolerant of the localized legacy format)."""
    out = []
    for line in text.splitlines():
        m = _SUMMARY_LINE.match(line.strip())
        if not m:
            continue
        # error/warn counts: first two integers after the line count
        rest = line[m.end():]
        nums = [int(x) for x in _NUM.findall(rest)]
        out.append(LogSummary(
            service=m.group("svc"), n_lines=int(m.group("lines")),
            n_error=nums[0] if nums else 0,
            n_warn=nums[1] if len(nums) > 1 else 0,
            size_bytes=_parse_size(m.group("size"))))
    return out


# substring + case-insensitive, matching the reference's `grep -c -i error`
# semantics (collect_log.sh:104-106); "exception" added for Java stacks
_LEVEL_PAT = [
    (re.compile(r"error|exception", re.I), LOG_ERROR),
    (re.compile(r"warn", re.I), LOG_WARN),
    (re.compile(r"info", re.I), LOG_INFO),
]
# ISO-ish timestamp prefix e.g. "2025-11-03 22:02:28" or "2025-11-03T22:02:28"
_TS_PAT = re.compile(r"(\d{4})-(\d{2})-(\d{2})[T ](\d{2}):(\d{2}):(\d{2})")


def parse_log_lines(text: str, service_idx: int,
                    default_t: float = 0.0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Line-level classification, reproducing the reference's grep -c -i
    info/warn/error counting (collect_log.sh:104-106).

    Dispatches to the C++ scanner (anomod.io.native) when built; the Python
    path below is the behavioral oracle."""
    from anomod.io import native
    if native.enabled():
        res = native.scan_log(text.encode("utf-8", errors="replace"))
        if res is not None:
            lvl, t = res
            t = np.where(t == 0.0, default_t, t)
            svc = np.full(lvl.shape[0], service_idx, np.int32)
            return svc, t, lvl.astype(np.int8)
    import calendar
    lines = text.splitlines()
    n = len(lines)
    svc = np.full(n, service_idx, np.int32)
    t = np.full(n, default_t, np.float64)
    lvl = np.full(n, LOG_OTHER, np.int8)
    for i, line in enumerate(lines):
        m = _TS_PAT.search(line[:64])
        if m:
            y, mo, d, h, mi, s = map(int, m.groups())
            t[i] = calendar.timegm((y, mo, d, h, mi, s, 0, 0, 0))
        for pat, code in _LEVEL_PAT:
            if pat.search(line):
                lvl[i] = code
                break
    return svc, t, lvl


def summarize_log_files(paths: List[Path],
                        service_of=lambda p: Path(p).stem.rsplit("_", 1)[0]
                        ) -> List[LogSummary]:
    """Per-file log summaries without building a LogBatch — the sweep of
    collect_log.sh:101-137 over an arbitrary file list.

    Native fast path: one parallel multi-file call into the C++ runtime
    (thread-pool executor + reusable read buffers); the Python loop below is
    the behavioral oracle.
    """
    from anomod.io import native
    paths = [Path(p) for p in paths]
    if native.enabled():
        res = native.summarize_log_files(paths)
        if res is not None:
            counts, _ts = res
            return [LogSummary(service=service_of(p), n_lines=int(c[0]),
                               n_error=int(c[3]), n_warn=int(c[2]),
                               n_info=int(c[1]), size_bytes=int(c[4]))
                    for p, c in zip(paths, counts)]
    out = []
    for p in paths:
        text = read_text_or_none(p)
        if text is None:
            out.append(LogSummary(service=service_of(p), n_lines=0,
                                  n_error=0, n_warn=0, n_info=0,
                                  size_bytes=0))
            continue
        _, _, lvl = parse_log_lines(text, 0)
        out.append(LogSummary(
            service=service_of(p), n_lines=len(lvl),
            n_error=int((lvl == LOG_ERROR).sum()),
            n_warn=int((lvl == LOG_WARN).sum()),
            n_info=int((lvl == LOG_INFO).sum()),
            size_bytes=p.stat().st_size))
    return out


def load_sn_log_dir(exp_dir: Path) -> Tuple[Optional[LogBatch], Optional[List[LogSummary]]]:
    exp_dir = Path(exp_dir)
    summaries = None
    stext = read_text_or_none(exp_dir / "summary.txt")
    if stext:
        summaries = parse_sn_summary(stext)
    services: Dict[str, int] = {}
    svc_col, t_col, lvl_col = [], [], []
    derived: List[LogSummary] = []
    for p in sorted(exp_dir.glob("*.log")):
        text = read_text_or_none(p)
        if text is None:
            continue
        svc_name = p.stem.rsplit("_", 1)[0]
        s_idx = services.setdefault(svc_name, len(services))
        svc, t, lvl = parse_log_lines(text, s_idx)
        svc_col.append(svc); t_col.append(t); lvl_col.append(lvl)
        derived.append(LogSummary(
            service=svc_name, n_lines=len(lvl),
            n_error=int((lvl == LOG_ERROR).sum()),
            n_warn=int((lvl == LOG_WARN).sum()),
            n_info=int((lvl == LOG_INFO).sum()),
            size_bytes=p.stat().st_size))
    if summaries is None and derived:
        # no (or stub) summary.txt: regenerate it from the already-parsed
        # lines, the way collect_log.sh:113-137 derives it at collection time
        summaries = derived
    batch = None
    if svc_col:
        batch = LogBatch(service=np.concatenate(svc_col),
                         t_s=np.concatenate(t_col),
                         level=np.concatenate(lvl_col),
                         services=tuple(services))
    return batch, summaries


_POD_HASH = re.compile(r"(-(?=[a-z0-9]*\d)[a-z0-9]{4,10}){1,2}$|-\d+$")


def pod_to_service(pod: str) -> str:
    """ts-order-service-86d6f7876-99bhf -> ts-order-service (log_collector.py:38-47)."""
    return _POD_HASH.sub("", pod)


def load_tt_log_dir(exp_dir: Path) -> Tuple[Optional[LogBatch], Optional[List[LogSummary]]]:
    exp_dir = Path(exp_dir)
    services: Dict[str, int] = {}
    svc_col, t_col, lvl_col = [], [], []
    summaries: List[LogSummary] = []
    for pod_dir in sorted(p for p in exp_dir.iterdir() if p.is_dir()):
        svc_name = pod_to_service(pod_dir.name)
        s_idx = services.setdefault(svc_name, len(services))
        for logf in sorted(pod_dir.glob("*.log")):
            if "_previous_" in logf.name:
                continue
            text = read_text_or_none(logf)
            if text is None:
                continue
            svc, t, lvl = parse_log_lines(text, s_idx)
            svc_col.append(svc); t_col.append(t); lvl_col.append(lvl)
            summaries.append(LogSummary(
                service=svc_name, n_lines=len(t),
                n_error=int((lvl == LOG_ERROR).sum()),
                n_warn=int((lvl == LOG_WARN).sum()),
                n_info=int((lvl == LOG_INFO).sum()),
                size_bytes=logf.stat().st_size))
    batch = None
    if svc_col:
        batch = LogBatch(service=np.concatenate(svc_col),
                         t_s=np.concatenate(t_col),
                         level=np.concatenate(lvl_col),
                         services=tuple(services))
    return batch, summaries or None


def load_tt_events(exp_dir: Path) -> Optional[list]:
    """kubernetes_events_*.json (log_collector.py:121-123)."""
    for p in sorted(Path(exp_dir).glob("kubernetes_events_*.json")):
        text = read_text_or_none(p)
        if text:
            try:
                doc = json.loads(text)
                return doc.get("items", doc) if isinstance(doc, dict) else doc
            except json.JSONDecodeError:
                return None
    return None
