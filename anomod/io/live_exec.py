"""Live exec-transport collectors: the subprocess-driven collection paths.

The reference's remaining live collectors do not speak HTTP — they shell
out: per-pod ``kubectl logs`` (current + ``--previous``) plus cluster
events (TT_collection-scripts/T-Dataset/log_collector.py:38-123), per
-container ``docker logs`` with the summary.txt pass
(SN_collection-scripts/Dataset/log_data/collect_log.sh:31-137), and the
JaCoCo ``jacococli dump`` + ``kubectl cp`` loop
(TT_collection-scripts/T-Dataset/coverage_tools/
collect_coverage_reports.sh:54-101).  This module is their exec-transport
half, mirroring how :mod:`anomod.io.live` is the HTTP-transport half:

  - ONE injectable :class:`ExecRunner` carries every subprocess call, so
    the full collection logic is testable against a fake runner
    (tests/test_live_exec.py) with no cluster anywhere — the same design
    that keeps the HTTP clients stub-server-tested.
  - collectors emit EXACTLY the artifact shapes the offline loaders
    consume: ``anomod.io.logs.load_tt_log_dir`` (pod dirs),
    ``load_sn_log_dir`` (<Display>_<ts>.log + summary.txt), and the
    ``coverage_data``/``coverage_report`` trees of
    ``anomod.io.coverage_report`` / ``anomod.io.coverage``.
"""

from __future__ import annotations

import dataclasses
import json
import re
import subprocess
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from anomod.io.live import CollectReport


@dataclasses.dataclass
class ExecResult:
    returncode: int
    stdout: str = ""
    stderr: str = ""


@dataclasses.dataclass
class ExecRunner:
    """Bounded subprocess transport shared by every exec collector.

    ``run_fn`` is injectable: tests swap in a fake that scripts the
    cluster's answers; production keeps the subprocess default.  A
    timeout or spawn failure degrades to a nonzero :class:`ExecResult`
    (collectors skip-and-continue, the reference scripts' behavior) —
    one wedged pod must not abort a whole collection sweep."""
    timeout: float = 60.0
    run_fn: Optional[Callable[[List[str]], ExecResult]] = None

    def run(self, cmd: List[str]) -> ExecResult:
        if self.run_fn is not None:
            return self.run_fn(list(cmd))
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=self.timeout)
            return ExecResult(r.returncode, r.stdout, r.stderr)
        except subprocess.TimeoutExpired:
            return ExecResult(124, "", f"timeout after {self.timeout}s")
        except OSError as e:
            return ExecResult(127, "", str(e))


# ---------------------------------------------------------------------------
# TT: kubectl log collection (log_collector.py:38-123)
# ---------------------------------------------------------------------------

_TT_POD_PREFIXES = ("ts-", "nacos", "rabbitmq")


@dataclasses.dataclass
class KubeLogCollector:
    """Per-pod ``kubectl logs`` sweep -> the load_tt_log_dir layout.

    ``<out>/<pod>/<pod>_<stamp>.log`` per running pod (current instance),
    ``<pod>_previous_<stamp>.log`` when the pod has a previous run (only
    written on rc==0 AND non-empty stdout — log_collector.py:100-107),
    plus ``kubernetes_events_<stamp>.json`` at the top level."""
    runner: ExecRunner = dataclasses.field(default_factory=ExecRunner)
    namespace: str = "default"

    def list_pods(self) -> List[str]:
        r = self.runner.run(["kubectl", "get", "pods", "--namespace",
                             self.namespace, "-o", "json"])
        if r.returncode != 0:
            return []
        try:
            items = json.loads(r.stdout).get("items", [])
        except json.JSONDecodeError:
            return []
        return [p["metadata"]["name"] for p in items
                if str(p.get("metadata", {}).get("name", ""))
                .startswith(_TT_POD_PREFIXES)]

    def collect(self, out_dir: Path, stamp: str, tail: int = 1000,
                with_events: bool = True) -> CollectReport:
        out_dir = Path(out_dir)
        files: List[str] = []
        skipped = 0
        n_lines = 0
        for pod in self.list_pods():
            cur = self.runner.run(["kubectl", "logs", pod, "--namespace",
                                   self.namespace, "--tail", str(tail)])
            if cur.returncode != 0:
                skipped += 1
            else:
                pod_dir = out_dir / pod
                pod_dir.mkdir(parents=True, exist_ok=True)
                path = pod_dir / f"{pod}_{stamp}.log"
                path.write_text(cur.stdout)
                files.append(str(path))
                n_lines += cur.stdout.count("\n")
            prev = self.runner.run(["kubectl", "logs", pod, "--namespace",
                                    self.namespace, "--previous"])
            if prev.returncode == 0 and prev.stdout.strip():
                pod_dir = out_dir / pod
                pod_dir.mkdir(parents=True, exist_ok=True)
                path = pod_dir / f"{pod}_previous_{stamp}.log"
                path.write_text(prev.stdout)
                files.append(str(path))
        if with_events:
            ev = self.runner.run(["kubectl", "get", "events", "-o", "json"])
            if ev.returncode == 0:
                out_dir.mkdir(parents=True, exist_ok=True)
                path = out_dir / f"kubernetes_events_{stamp}.json"
                path.write_text(ev.stdout)
                files.append(str(path))
        return CollectReport(kind="kubectl_logs", files=tuple(files),
                             n_records=n_lines, n_skipped=skipped)


# ---------------------------------------------------------------------------
# SN: docker log collection + summary (collect_log.sh:31-137)
# ---------------------------------------------------------------------------

SN_LOG_SERVICES: Tuple[str, ...] = (
    "compose-post-service", "post-storage-service", "user-service",
    "user-mention-service", "unique-id-service", "media-service",
    "social-graph-service", "user-timeline-service", "url-shorten-service",
    "home-timeline-service", "text-service", "nginx-thrift")


def _compose_container_re(project: str, svc: str):
    """The compose v1 container-name convention
    (``<project>_<service>_<replica>``) — single source for every
    collector that locates SN containers."""
    return re.compile(rf"{re.escape(project)}_{re.escape(svc)}_\d+")


def _display_name(svc: str) -> str:
    """compose-post-service -> ComposePostService (collect_log.sh's
    DISPLAY_NAMES table, derived instead of hand-enumerated)."""
    return "".join(w.capitalize() for w in svc.split("-"))


@dataclasses.dataclass
class DockerLogCollector:
    """``docker ps`` + per-container ``docker logs`` sweep -> the
    load_sn_log_dir layout: ``<Display>_<stamp>.log`` per service plus
    the ``summary.txt`` contract (collect_log.sh:101-137 — per-service
    size/lines and error/warn counts; a service with no running
    container is skipped with a 未找到日志文件 row, the stop-fault
    fingerprint the golden run's absence tier reads)."""
    runner: ExecRunner = dataclasses.field(default_factory=ExecRunner)
    services: Sequence[str] = SN_LOG_SERVICES
    compose_project: str = "socialnetwork"

    def _container_ids(self) -> Dict[str, str]:
        r = self.runner.run(["docker", "ps", "--format",
                             "{{.ID}} {{.Names}}"])
        if r.returncode != 0:
            return {}
        out: Dict[str, str] = {}
        for line in r.stdout.splitlines():
            parts = line.split(None, 1)
            if len(parts) != 2:
                continue
            cid, cname = parts
            for svc in self.services:
                if _compose_container_re(self.compose_project,
                                         svc).search(cname):
                    out[svc] = cid
        return out

    def collect(self, out_dir: Path, stamp: str,
                time_range: Optional[str] = None) -> CollectReport:
        out_dir = Path(out_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        containers = self._container_ids()
        files: List[str] = []
        skipped = 0
        total_lines = 0
        # load_sn_log_dir derives the service via stem.rsplit('_', 1)[0],
        # so the filename stamp must carry NO underscore or every derived
        # service name would absorb the stamp's first segment
        fstamp = stamp.replace("_", "-")
        summary = [f"Collection timestamp: {stamp}",
                   "Time window: " + (time_range or "full history"),
                   f"Services captured: {len(self.services)}", "",
                   "Log file summary:"]
        for svc in self.services:
            display = _display_name(svc)
            cid = containers.get(svc)
            if cid is None:
                summary.append(f"- {display}: 未找到日志文件")
                skipped += 1
                continue
            cmd = ["docker", "logs"]
            if time_range:
                cmd += ["--since", time_range]
            r = self.runner.run(cmd + [cid])
            if r.returncode != 0:
                summary.append(f"- {display}: 未找到日志文件")
                skipped += 1
                continue
            text = r.stdout
            path = out_dir / f"{display}_{fstamp}.log"
            path.write_text(text)
            files.append(str(path))
            lines = text.splitlines()
            total_lines += len(lines)
            # LINE counts, the grep -c -i contract (collect_log.sh:129-131)
            # — substring totals would double-count "ERROR: upstream error"
            n_err = sum(1 for l in lines if "error" in l.lower())
            n_warn = sum(1 for l in lines if "warn" in l.lower())
            n_start = sum(1 for l in lines if "Starting" in l)
            summary.append(
                f"- {display}: {max(path.stat().st_size // 1024, 1)}K "
                f"({len(lines)} lines) | errors={n_err}, "
                f"warnings={n_warn}, startup={n_start}")
        spath = out_dir / "summary.txt"
        spath.write_text("\n".join(summary) + "\n")
        files.append(str(spath))
        return CollectReport(kind="docker_logs", files=tuple(files),
                             n_records=total_lines, n_skipped=skipped)


# ---------------------------------------------------------------------------
# SN: gcov flush + in-container collection (collect_all_data.sh:500-560)
# ---------------------------------------------------------------------------

SN_GCOV_SERVICES: Tuple[str, ...] = tuple(
    s for s in SN_LOG_SERVICES if s != "nginx-thrift")


@dataclasses.dataclass
class GcovCoverageCollector:
    """The SN gcov collection loop: SIGUSR1 flush + per-container collect
    script + host-mounted report pickup.

    Contract (collect_all_data.sh:500-560): every running
    ``socialnetwork_*service`` container gets ``kill -USR1 1`` (the gcov
    flush signal), then each service container runs its baked-in
    ``/usr/local/bin/collect_coverage.sh`` with EXPERIMENT_BASE_NAME /
    SERVICE_NAME / TIMESTAMP env, writing ``.gcov`` text into the
    compose-mounted ``coverage-reports/<base>_<stamp>/<service>/``; the
    host then moves that tree into
    ``coverage_data/`` where :func:`anomod.io.coverage.load_sn_coverage_dir`
    reads per-service dirs of ``.gcov`` files."""
    runner: ExecRunner = dataclasses.field(default_factory=ExecRunner)
    services: Sequence[str] = SN_GCOV_SERVICES
    compose_project: str = "socialnetwork"

    def _running(self) -> List[str]:
        """One ``docker ps`` listing shared by flush + per-service lookup
        (a wedged daemon must cost one timeout, not one per service)."""
        r = self.runner.run(["docker", "ps", "--filter",
                             f"name={self.compose_project}_.*service",
                             "--format", "{{.Names}}"])
        return r.stdout.split() if r.returncode == 0 else []

    def _flush(self, running: Sequence[str]) -> int:
        """SIGUSR1 every running service container; returns the count."""
        n = 0
        for cname in running:
            if self.runner.run(["docker", "exec", cname, "kill", "-USR1",
                                "1"]).returncode == 0:
                n += 1
        return n

    def collect(self, mount_root: Path, out_dir: Path, base: str,
                stamp: str) -> CollectReport:
        """Flush, run each container's collect script, then move the
        host-mounted report tree to its ``coverage_data`` home."""
        import shutil
        running = self._running()
        flushed = self._flush(running)
        skipped = 0
        for svc in self.services:
            # any replica suffix, the same convention the log collector
            # matches — a service recreated as _2 must still be collected
            pat = _compose_container_re(self.compose_project, svc)
            cname = next((c for c in running if pat.fullmatch(c)), None)
            if cname is None:
                skipped += 1
                continue
            r = self.runner.run(
                ["docker", "exec",
                 "-e", f"EXPERIMENT_BASE_NAME={base}",
                 "-e", f"SERVICE_NAME={svc}",
                 "-e", f"TIMESTAMP={stamp}",
                 cname, "/usr/local/bin/collect_coverage.sh"])
            if r.returncode != 0:
                skipped += 1
        src = Path(mount_root) / f"{base}_{stamp}"
        out_dir = Path(out_dir)
        files: List[str] = []
        notes = [f"flushed={flushed}"]
        if src.is_dir():
            if out_dir.exists():
                # moving INTO an existing dir would nest the tree one
                # level deep — a shape load_sn_coverage_dir cannot read;
                # degrade loudly instead of corrupting silently
                notes.append(f"target exists, not moved: {out_dir}")
            else:
                out_dir.parent.mkdir(parents=True, exist_ok=True)
                shutil.move(str(src), str(out_dir))
                files = [str(p) for p in sorted(out_dir.rglob("*.gcov"))]
        return CollectReport(kind="gcov_coverage", files=tuple(files),
                             n_records=len(files), n_skipped=skipped,
                             notes=tuple(notes))


# ---------------------------------------------------------------------------
# TT: JaCoCo dump + cp loop (collect_coverage_reports.sh:54-101)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JacocoCoverageCollector:
    """The jacococli dump/pull loop over ts- pods.

    Per pod: probe for the CLI jar, ``jacococli dump --reset`` into
    ``/coverage/jacoco-<pod>.exec``, list exec files, and ``kubectl cp``
    each to ``<exec_dir>/<pod>__<basename>`` — then the offline
    :func:`anomod.io.coverage_report.collect_coverage_reports` pipeline
    merges per service and renders the ``coverage_report`` tree the
    loaders read.  Our binary dump format is the CoverageDump ``.npz``
    (the ``.exec`` analog), so a fake runner "cp"s by writing one."""
    runner: ExecRunner = dataclasses.field(default_factory=ExecRunner)
    namespace: str = "default"
    port: int = 6300

    def _pods(self) -> List[str]:
        r = self.runner.run(["kubectl", "-n", self.namespace, "get", "pods",
                             "-l", "app", "-o",
                             "jsonpath={.items[*].metadata.name}"])
        if r.returncode != 0:
            return []
        return [p for p in r.stdout.split() if p.startswith("ts-")]

    def pull_execs(self, exec_dir: Path) -> Tuple[List[Path], int]:
        """Dump + pull every pod's exec files; returns (paths, skipped)."""
        exec_dir = Path(exec_dir)
        exec_dir.mkdir(parents=True, exist_ok=True)
        pulled: List[Path] = []
        skipped = 0
        for pod in self._pods():
            probe = self.runner.run(
                ["kubectl", "-n", self.namespace, "exec", pod, "--", "sh",
                 "-c", "test -f /jacoco/jacococli.jar"])
            if probe.returncode != 0:
                skipped += 1
                continue
            dump = self.runner.run(
                ["kubectl", "-n", self.namespace, "exec", pod, "--", "sh",
                 "-c",
                 f"mkdir -p /coverage && env -u JAVA_TOOL_OPTIONS java -jar "
                 f"/jacoco/jacococli.jar dump --address localhost --port "
                 f"{self.port} --destfile /coverage/jacoco-{pod}.exec "
                 f"--reset"])
            if dump.returncode != 0:
                skipped += 1
                continue
            ls = self.runner.run(
                ["kubectl", "-n", self.namespace, "exec", pod, "--", "sh",
                 "-c", "ls -1 /coverage/*.exec 2>/dev/null || true"])
            for f in ls.stdout.split():
                base = f.rsplit("/", 1)[-1]
                dst = exec_dir / f"{pod}__{base}"
                cp = self.runner.run(
                    ["kubectl", "-n", self.namespace, "cp",
                     f"{pod}:{f}", str(dst)])
                if cp.returncode == 0 and dst.exists():
                    pulled.append(dst)
                else:
                    skipped += 1
        return pulled, skipped

    def collect(self, data_dir: Path, report_dir: Path) -> CollectReport:
        """Full pipeline: dump/pull execs, then merge + render the
        ``coverage_report`` tree per service (the .sh script's follow-on
        coverage_summary.py stage)."""
        from anomod.io.coverage_report import (collect_coverage_reports,
                                               load_dump)
        from anomod.io.logs import pod_to_service
        pulled, skipped = self.pull_execs(data_dir)
        dumps_by_pod: Dict[str, List] = {}
        for path in pulled:
            pod = path.name.split("__", 1)[0]
            try:
                d = load_dump(path)
            except Exception:
                skipped += 1
                continue
            # dump ownership follows the POD the exec came from (the
            # reference merges per service by pod name)
            d = dataclasses.replace(d, service=pod_to_service(pod))
            dumps_by_pod.setdefault(pod, []).append(d)
        totals = collect_coverage_reports(dumps_by_pod, data_dir,
                                          report_dir)
        files = tuple(str(p) for p in pulled)
        return CollectReport(
            kind="jacoco_coverage", files=files,
            n_records=sum(t["lines_covered"] for t in totals.values()),
            n_skipped=skipped,
            notes=tuple(f"{s}: {t['lines_covered']}/{t['lines_total']}"
                        for s, t in sorted(totals.items())))
