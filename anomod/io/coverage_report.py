"""Coverage dump / merge / report pipeline — the jacococli analog.

The reference's TT coverage path is: JaCoCo agents expose a tcpserver dump
port; per pod, ``jacococli dump --reset`` pulls a binary ``.exec`` file
(collect_coverage_reports.sh:54-63); per service, exec files are merged
(``jacococli merge``, coverage_summary.py:40-65), rendered to XML+HTML
(:68-94), and the top-level LINE counter becomes ``coverage-summary.txt``
(:97-125).

Here the ``.exec`` analog is a :class:`CoverageDump`: per source file, a
boolean covered-line mask (what JaCoCo's probe array encodes, reduced to line
granularity).  Merge is exact — element-wise OR, the same union-of-probes
semantics as ``jacococli merge`` — and reports are written in the reference's
exact artifact shapes (JaCoCo XML LINE counters; the boxed summary text that
`parse_summary_txt` in :mod:`anomod.io.coverage` reads back).  Dumps
serialize to ``.npz`` (our binary wire format) so a campaign can archive
per-pod dumps the way ``kubectl cp`` archives exec files.
"""

from __future__ import annotations

import dataclasses
import io as _io
from pathlib import Path
from typing import Dict, Iterable, List, Sequence

import numpy as np

from anomod.schemas import CoverageBatch, FileCoverage, coverage_batch_from_files


@dataclasses.dataclass
class CoverageDump:
    """Per-service covered-line masks, keyed by source path."""
    service: str
    files: Dict[str, np.ndarray]   # path → bool[n_lines]

    @property
    def lines_total(self) -> int:
        return int(sum(m.size for m in self.files.values()))

    @property
    def lines_covered(self) -> int:
        return int(sum(int(m.sum()) for m in self.files.values()))

    def to_file_coverage(self) -> List[FileCoverage]:
        return [FileCoverage(self.service, path, int(m.size), int(m.sum()))
                for path, m in sorted(self.files.items())]


def merge_dumps(dumps: Sequence[CoverageDump]) -> CoverageDump:
    """Union-of-probes merge (jacococli merge semantics): a line is covered
    if any dump covered it; files union; length mismatches pad with
    uncovered."""
    if not dumps:
        raise ValueError("nothing to merge")
    service = dumps[0].service
    if any(d.service != service for d in dumps):
        raise ValueError("merge_dumps merges one service at a time")
    merged: Dict[str, np.ndarray] = {}
    for d in dumps:
        for path, mask in d.files.items():
            mask = np.asarray(mask, bool)
            if path not in merged:
                merged[path] = mask.copy()
                continue
            a = merged[path]
            if a.size < mask.size:
                a = np.pad(a, (0, mask.size - a.size))
            elif mask.size < a.size:
                mask = np.pad(mask, (0, a.size - mask.size))
            merged[path] = a | mask
    return CoverageDump(service, merged)


def save_dump(dump: CoverageDump, path: Path) -> None:
    """Binary archive of one dump (the `.exec` analog, npz wire format)."""
    arrays = {f"mask_{i}": np.packbits(m)
              for i, m in enumerate(dump.files.values())}
    sizes = np.array([m.size for m in dump.files.values()], np.int64)
    names = np.array(list(dump.files.keys()))
    np.savez_compressed(path, service=np.array(dump.service), names=names,
                        sizes=sizes, **arrays)


def load_dump(path: Path) -> CoverageDump:
    with np.load(path, allow_pickle=False) as z:
        names = [str(n) for n in z["names"]]
        sizes = z["sizes"]
        files = {}
        for i, (name, size) in enumerate(zip(names, sizes)):
            files[name] = np.unpackbits(z[f"mask_{i}"])[:int(size)].astype(bool)
        return CoverageDump(str(z["service"][()]), files)


# ---------------------------------------------------------------------------
# Report rendering (coverage_summary.py artifact shapes)
# ---------------------------------------------------------------------------

def write_jacoco_xml(dump: CoverageDump) -> str:
    """JaCoCo-shaped XML: per-sourcefile LINE counters + a report-level LINE
    counter (the element `parse_jacoco_xml` and the reference's
    parse_total_from_xml read)."""
    parts = [f'<?xml version="1.0" encoding="UTF-8"?>'
             f'<report name="{dump.service}">',
             f'<package name="{dump.service}">']
    for path, mask in sorted(dump.files.items()):
        covered = int(mask.sum())
        missed = int(mask.size) - covered
        parts.append(f'<sourcefile name="{path}">'
                     f'<counter type="LINE" missed="{missed}" '
                     f'covered="{covered}"/></sourcefile>')
    parts.append("</package>")
    missed_total = dump.lines_total - dump.lines_covered
    parts.append(f'<counter type="LINE" missed="{missed_total}" '
                 f'covered="{dump.lines_covered}"/>')
    parts.append("</report>")
    return "".join(parts)


def write_summary_txt(service: str, lines_total: int, lines_covered: int) -> str:
    """The boxed coverage-summary.txt (coverage_summary.py:110-125 shape,
    e.g. TT_data/.../ts-order-service/coverage-summary.txt:6)."""
    pct = 0 if lines_total == 0 else int(round(100 * lines_covered / lines_total))
    bar = "-" * 66
    return ("=" * 66 + "\n"
            "  Simple Code Coverage Report\n"
            f"{bar}\n"
            f"Service: {service}\n"
            f"{bar}\n"
            + "TOTAL".ljust(20) + f"Lines {lines_total:6d}  Cover {pct:3d}%\n"
            + f"{bar}\n")


def parse_total_from_xml(text: str) -> Dict[str, int]:
    """Top-level LINE counter from report XML (coverage_summary.py:97-108)."""
    import xml.etree.ElementTree as ET
    root = ET.parse(_io.StringIO(text)).getroot()
    for c in root.findall("counter"):
        if c.get("type") == "LINE":
            return {"covered": int(c.get("covered")),
                    "missed": int(c.get("missed"))}
    return {"covered": 0, "missed": 0}


# ---------------------------------------------------------------------------
# Batch ↔ dump bridges + collection orchestration
# ---------------------------------------------------------------------------

def batch_to_dumps(batch: CoverageBatch, seed: int = 0) -> List[CoverageDump]:
    """Expand counter rows into per-service dumps with concrete line masks.

    Covered lines are placed deterministically (seeded per file) — the
    counter marginals are preserved exactly, so batch → dumps → report
    round-trips the totals."""
    rng = np.random.default_rng(seed)
    by_service: Dict[str, Dict[str, np.ndarray]] = {}
    for fi in range(len(batch.paths)):
        svc = batch.services[int(batch.service[fi])]
        total = int(batch.lines_total[fi])
        covered = int(batch.lines_covered[fi])
        mask = np.zeros(total, bool)
        if covered:
            mask[rng.choice(total, size=covered, replace=False)] = True
        by_service.setdefault(svc, {})[batch.paths[fi]] = mask
    return [CoverageDump(svc, files) for svc, files in
            sorted(by_service.items())]


def dumps_to_batch(dumps: Sequence[CoverageDump]) -> CoverageBatch:
    files: List[FileCoverage] = []
    for d in dumps:
        files += d.to_file_coverage()
    return coverage_batch_from_files(files)


def collect_coverage_reports(dumps_by_pod: Dict[str, Sequence[CoverageDump]],
                             data_dir: Path, report_dir: Path) -> Dict[str, dict]:
    """The collect_coverage_reports.sh pipeline over in-memory dumps:
    archive each pod's dump (`coverage_data/<pod>__jacoco-<pod>.npz`), then
    per service merge → xml + summary (`coverage_report/<svc>/...`).
    Returns per-service totals."""
    data_dir = Path(data_dir)
    report_dir = Path(report_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    per_service: Dict[str, List[CoverageDump]] = {}
    for pod, dumps in sorted(dumps_by_pod.items()):
        for i, d in enumerate(dumps):
            save_dump(d, data_dir / f"{pod}__jacoco-{pod}-{i}.npz")
            per_service.setdefault(d.service, []).append(d)
    totals: Dict[str, dict] = {}
    for svc, dumps in sorted(per_service.items()):
        merged = merge_dumps(dumps)
        sdir = report_dir / svc
        sdir.mkdir(parents=True, exist_ok=True)
        save_dump(merged, sdir / "merged.npz")
        (sdir / "coverage.xml").write_text(write_jacoco_xml(merged))
        (sdir / "coverage-summary.txt").write_text(
            write_summary_txt(svc, merged.lines_total, merged.lines_covered))
        totals[svc] = {"lines_total": merged.lines_total,
                       "lines_covered": merged.lines_covered}
    return totals
