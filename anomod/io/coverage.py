"""Coverage loaders → CoverageBatch.

SN: gcov text per service dir — files named ``#path#to#file.gcov`` with lines
``<count>:<lineno>:<source>`` where count ``-`` = non-executable, ``#####`` =
uncovered (the materialized content in SN_data/coverage_data).

TT: JaCoCo — ``coverage-summary.txt`` ("TOTAL  Lines  500  Cover  43%",
coverage_summary.py:97-125) and ``coverage.xml`` LINE counters
(``<counter type="LINE" missed=".." covered=".."/>``).
"""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import List, Optional

from anomod.io.lfs import is_lfs_pointer, read_text_or_none
from anomod.schemas import CoverageBatch, FileCoverage, coverage_batch_from_files

#: Ingest-cache key component (anomod.io.cache): bump when this module's
#: parsing semantics change, invalidating exactly the coverage entries.
LOADER_VERSION = 1

_GCOV_LINE = re.compile(r"^\s*([#\-\d]+[*]?):\s*(\d+):")
_SUMMARY_TOTAL = re.compile(r"TOTAL\s+Lines\s+(\d+)\s+Cover\s+(\d+)%")


def parse_gcov(text: str, service: str, path: str) -> FileCoverage:
    total = covered = 0
    for line in text.splitlines():
        m = _GCOV_LINE.match(line)
        if not m:
            continue
        count = m.group(1).rstrip("*")
        if count == "-":
            continue
        total += 1
        if count != "#####" and count != "=====":
            covered += 1
    return FileCoverage(service=service, path=path,
                        lines_total=total, lines_covered=covered)


def load_sn_coverage_dir(exp_dir: Path) -> Optional[CoverageBatch]:
    """Per-service dirs of .gcov text (SN_data/coverage_data/<exp>/<svc>/)."""
    exp_dir = Path(exp_dir)
    files: List[FileCoverage] = []
    for svc_dir in sorted(p for p in exp_dir.iterdir() if p.is_dir()):
        for g in sorted(svc_dir.glob("*.gcov")):
            text = read_text_or_none(g)
            if text is None:
                continue
            src = g.name.replace("#", "/").removesuffix(".gcov")
            files.append(parse_gcov(text, svc_dir.name, src))
    return coverage_batch_from_files(files) if files else None


def parse_jacoco_xml(text: str, service: str) -> List[FileCoverage]:
    """Extract per-sourcefile LINE counters from a JaCoCo report XML."""
    out: List[FileCoverage] = []
    try:
        root = ET.fromstring(text)
    except ET.ParseError:
        return out
    for pkg in root.iter("package"):
        pkg_name = pkg.get("name", "")
        for sf in pkg.findall("sourcefile"):
            for c in sf.findall("counter"):
                if c.get("type") == "LINE":
                    missed = int(c.get("missed", 0))
                    covered = int(c.get("covered", 0))
                    out.append(FileCoverage(
                        service=service,
                        path=f"{pkg_name}/{sf.get('name', '')}",
                        lines_total=missed + covered,
                        lines_covered=covered))
    return out


def parse_summary_txt(text: str, service: str) -> Optional[FileCoverage]:
    """coverage-summary.txt TOTAL line (coverage_summary.py:97-125)."""
    m = _SUMMARY_TOTAL.search(text)
    if not m:
        return None
    total = int(m.group(1))
    pct = int(m.group(2))
    return FileCoverage(service=service, path="TOTAL",
                        lines_total=total, lines_covered=total * pct // 100)


def load_tt_coverage_report(report_dir: Path) -> Optional[CoverageBatch]:
    """TT_data/coverage_report/<exp>/<svc>/{coverage.xml,coverage-summary.txt}."""
    report_dir = Path(report_dir)
    files: List[FileCoverage] = []
    for svc_dir in sorted(p for p in report_dir.iterdir() if p.is_dir()):
        svc = svc_dir.name
        xml_text = read_text_or_none(svc_dir / "coverage.xml")
        if xml_text:
            per_file = parse_jacoco_xml(xml_text, svc)
            if per_file:
                files.extend(per_file)
                continue
        sum_text = read_text_or_none(svc_dir / "coverage-summary.txt")
        if sum_text:
            fc = parse_summary_txt(sum_text, svc)
            if fc:
                files.append(fc)
    return coverage_batch_from_files(files) if files else None
