"""Double-buffered host→device staging for the replay/stream hot paths.

The input-bound pattern the GNN-DSA paper (PAPERS.md) attacks, applied to
the corpus pipeline: while the jitted replay/stream dispatch consumes chunk
``i`` on device, a background thread is already pushing chunk ``i+1``
through ``jax.device_put`` — so the accelerator never waits on host-side
chunk prep, and host packing of column ``j+1`` overlaps the H2D copy of
column ``j`` during whole-corpus staging.

Host-only consumers never import jax through this module: the device put is
resolved lazily inside the worker thread, and :class:`Pipeline` itself is a
generic bounded producer/consumer usable with any staging function.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import numpy as np

from anomod import obs

_SENTINEL = object()


class Pipeline:
    """Bounded background-staging iterator (the double buffer).

    A worker thread pulls items from ``iterable``, applies ``fn`` (the
    staging step — typically ``jax.device_put``), and parks at most
    ``depth`` staged results in a queue; the consumer iterates the staged
    results in order.  ``depth=2`` is classic double buffering: one item
    in flight on the device, one staged ahead.  Worker exceptions are
    re-raised in the consumer.  A consumer that stops early (break,
    exception) MUST call :meth:`close` — a ``finally`` block at every
    in-repo call site — or the worker stays parked on the bounded queue
    holding staged buffers; a dropped Pipeline makes a best-effort
    ``close`` from ``__del__`` as a backstop.
    """

    def __init__(self, iterable: Iterable[Any],
                 fn: Callable[[Any], Any], depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._err: Optional[BaseException] = None
        # staging telemetry: per-item staging wall (the host->device
        # transfer seconds when fn is device_put) + the buffer occupancy
        # the consumer sees — a persistently full queue means the device
        # is the bottleneck, a persistently empty one means the host is
        stage_s = obs.histogram("anomod_prefetch_stage_seconds")
        # one handle shared by producer AND consumer (__next__): cached
        # here so the per-item hot path never pays a registry lookup and
        # a mid-iteration set_registry swap can't split the two sides
        # across registries
        self._occupancy = obs.gauge("anomod_prefetch_queue_depth")
        occupancy = self._occupancy

        def work():
            try:
                for item in iterable:
                    if self._stop.is_set():
                        return
                    t0 = time.perf_counter()
                    staged = fn(item)
                    stage_s.observe(time.perf_counter() - t0)
                    self._q.put(staged)
                    occupancy.set(self._q.qsize())
            except BaseException as e:       # re-raised on the consumer side
                self._err = e
            finally:
                self._q.put(_SENTINEL)

        self._thread = threading.Thread(target=work, daemon=True,
                                        name="anomod-prefetch")
        self._thread.start()

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        self._occupancy.set(self._q.qsize())
        if item is _SENTINEL:
            self._done = True
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the worker and drain; safe to call more than once.
        Free after normal exhaustion (the sentinel was already seen)."""
        if self._done:
            return
        self._stop.set()
        while True:
            try:
                if self._q.get(timeout=0.05) is _SENTINEL:
                    break
            except queue.Empty:
                if not self._thread.is_alive():
                    break
        self._done = True
        self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _device_put(x):
    import jax
    return jax.device_put(x)


def prefetch_to_device(iterable: Iterable[Any], depth: int = 2,
                       put: Optional[Callable[[Any], Any]] = None) -> Pipeline:
    """Stage each item to device in a background thread, ``depth`` ahead."""
    return Pipeline(iterable, put or _device_put, depth=depth)


def iter_chunk_dicts(chunks: Dict[str, np.ndarray]) -> Iterator[Dict[str, Any]]:
    """Per-chunk row dicts from stage_columns' stacked [n_chunks, C] arrays."""
    n_chunks = next(iter(chunks.values())).shape[0]
    for i in range(n_chunks):
        yield {k: v[i] for k, v in chunks.items()}


def device_put_columns(columns: Dict[str, np.ndarray],
                       depth: int = 2) -> Dict[str, Any]:
    """Stage a column dict to device with per-column transfer overlap.

    Columns are put one at a time from the background thread while the
    consumer collects the previous ones — on real hardware this overlaps
    the H2D copy of column ``j`` with the dispatch bookkeeping of ``j+1``;
    on CPU backends it degrades to a plain device_put loop.
    """
    staged = prefetch_to_device(
        list(columns.items()), depth=depth,
        put=lambda kv: (kv[0], _device_put(kv[1])))
    try:
        return dict(staged)
    finally:
        staged.close()
