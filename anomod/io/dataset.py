"""Dataset discovery + experiment loading with synthetic fallback.

Archive layout (SURVEY.md §2.3 / L7):
  SN_data/{log,metric,trace,coverage}_data + api_responses, experiment dirs
  named ``<Exp>_<YYYYMMDD_HHMMSS>_<modality>_<...>`` (collect_all_data.sh:207-211).
  TT_data/{log,metric,trace,api_responses,coverage_data,coverage_report}
  with dirs named ``<Lv_*|Normal_case>_<ISO8601>_em`` (T-Dataset/README.md:9-17).

Every payload that is a git-LFS pointer stub falls back to the deterministic
synthetic generator (config.synth_on_lfs), keeping the full 2x13-experiment
corpus loadable from the shipped checkout.

Ingest fast path (anomod.io.cache): every parsed or synth-generated modality
is read through the content-addressed cache — keyed by loader version +
source-file stat fingerprint (parsed) or generator version + label + seed +
n_traces (synth) — so warm loads skip CSV/JSON/gcov parsing and synth
regeneration entirely.  ``load_corpus`` additionally fans experiments across
a spawn-context process pool (``Config.ingest_workers`` / the ``workers``
argument); the serial path is kept and parity-tested.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from anomod import labels as labels_mod
from anomod import synth
from anomod.config import Config, get_config
from anomod.io import api as api_io
from anomod.io import cache
from anomod.io import coverage as cov_io
from anomod.io import logs as logs_io
from anomod.io import metrics as met_io
from anomod.io import sn_traces, tt_traces
from anomod.schemas import Experiment

_SN_MODALITY_DIRS = {
    "traces": "trace_data", "metrics": "metric_data", "logs": "log_data",
    "api": "api_responses", "coverage": "coverage_data",
}
_TT_MODALITY_DIRS = {
    "traces": "trace_data", "metrics": "metric_data", "logs": "log_data",
    "api": "api_responses", "coverage": "coverage_report",
}

MODALITIES = ("traces", "metrics", "logs", "api", "coverage")


@dataclasses.dataclass
class ExperimentDirs:
    name: str                      # canonical experiment name
    testbed: str
    dirs: Dict[str, Path]          # modality -> experiment dir


def discover(testbed: str, cfg: Optional[Config] = None) -> List[ExperimentDirs]:
    """Walk the archive tree, grouping modality dirs by canonical experiment."""
    cfg = cfg or get_config()
    root = cfg.sn_data if testbed == "SN" else cfg.tt_data
    modality_dirs = _SN_MODALITY_DIRS if testbed == "SN" else _TT_MODALITY_DIRS
    found: Dict[str, ExperimentDirs] = {}
    for modality, sub in modality_dirs.items():
        base = root / sub
        if not base.is_dir():
            continue
        for d in sorted(base.iterdir()):
            if not d.is_dir():
                continue
            canon = labels_mod.canonical_experiment(d.name)
            if labels_mod.label_for(canon) is None:
                continue
            ed = found.setdefault(canon, ExperimentDirs(canon, testbed, {}))
            ed.dirs.setdefault(modality, d)
    return list(found.values())


def loader_version(modality: str, testbed: str) -> int:
    """The owning loader module's LOADER_VERSION — part of the cache key, so
    bumping one loader invalidates exactly its modality's entries."""
    if modality == "traces":
        mod = tt_traces if testbed == "TT" else sn_traces
    else:
        mod = {"metrics": met_io, "logs": logs_io, "api": api_io,
               "coverage": cov_io}[modality]
    return mod.LOADER_VERSION


def _parse_modality(modality: str, testbed: str, d: Path):
    """Run the raw (uncached) loader for one modality dir.

    Value conventions: ``logs`` yields the ``(LogBatch|None, summaries)``
    pair; every other modality yields its batch or None.
    """
    if modality == "traces":
        if testbed == "TT":
            art = tt_traces.find_trace_artifact(d)
            return tt_traces.load_skywalking_json(art) if art else None
        art = sn_traces.find_trace_artifact(d)
        if art and art.suffix == ".json":
            return sn_traces.load_jaeger_json(art)
        return sn_traces.load_jaeger_csv(art) if art else None
    if modality == "metrics":
        if testbed == "TT":
            art = met_io.find_tt_metric_artifact(d)
            return met_io.load_tt_metric_csv(art) if art else None
        return met_io.load_sn_metric_dir(d)
    if modality == "logs":
        loader = (logs_io.load_tt_log_dir if testbed == "TT"
                  else logs_io.load_sn_log_dir)
        return loader(d)
    if modality == "api":
        art = api_io.find_api_artifact(d)
        return api_io.load_api_jsonl(art) if art else None
    if modality == "coverage":
        loader = (cov_io.load_tt_coverage_report if testbed == "TT"
                  else cov_io.load_sn_coverage_dir)
        return loader(d)
    raise ValueError(f"unknown modality {modality!r}")


def _synth_modality(modality: str, label, n_synth_traces: int):
    if modality == "traces":
        return synth.generate_spans(label, n_traces=n_synth_traces)
    if modality == "metrics":
        return synth.generate_metrics(label)
    if modality == "logs":
        return synth.generate_logs(label)
    if modality == "api":
        return synth.generate_api(label)
    if modality == "coverage":
        return synth.generate_coverage(label)
    raise ValueError(f"unknown modality {modality!r}")


def _cache_kind(modality: str) -> str:
    return {"traces": "spans", "metrics": "metrics", "logs": "logs",
            "api": "api", "coverage": "coverage"}[modality]


def synth_key_parts(modality: str, label, n_synth_traces: int,
                    cfg: Config) -> dict:
    """Cache key parts for a synth-fallback modality: generator version +
    label (+ n_traces for the trace generator).  The generators derive
    their seeds from the label name alone (synth._seed_for), so no config
    seed belongs in the key — it would only manufacture spurious misses."""
    parts = {
        "source": "synth",
        "synth_version": synth.SYNTH_VERSION,
        "modality": modality,
        "testbed": label.testbed,
        "experiment": label.experiment,
    }
    if modality == "traces":
        parts["n_traces"] = n_synth_traces
    return parts


def _source_key_parts(modality: str, testbed: str, experiment: str,
                      d: Path) -> dict:
    return {
        "source": "parse",
        "loader_version": loader_version(modality, testbed),
        "modality": modality,
        "testbed": testbed,
        "experiment": experiment,
        "fingerprint": cache.dir_fingerprint(d),
    }


def _modality_present(modality: str, value) -> bool:
    if modality == "logs":
        return value is not None and value[0] is not None
    return value is not None


def _load_modality(modality: str, label, testbed: str, d: Optional[Path],
                   n_synth_traces: int, cfg: Config):
    """One modality through the cache: parse path first, synth fallback.

    Returns ``(value, synthetic)`` with the logs pair convention.  Parsed
    results that come back empty are not cached (the parse was cheap);
    partial logs results (real summaries, no lines) ARE cached.
    """
    value = None
    caching = cache.cache_root(cfg) is not None
    if d is not None:
        if caching:
            def cacheable(v):
                if modality == "logs":
                    return v is not None and (v[0] is not None
                                              or (v[1] or None) is not None)
                return v is not None
            value, _, _ = cache.cached(
                _cache_kind(modality),
                _source_key_parts(modality, testbed, label.experiment, d),
                lambda: _parse_modality(modality, testbed, d),
                cfg=cfg, cacheable=cacheable)
        else:
            # no cache root: don't pay the source-fingerprint dir walk
            # for a key nobody will use
            value = _parse_modality(modality, testbed, d)
    if modality == "logs" and value is None:
        value = (None, None)
    if _modality_present(modality, value) or not cfg.synth_on_lfs:
        return value, False
    syn, _, _ = cache.cached(
        _cache_kind(modality),
        synth_key_parts(modality, label, n_synth_traces, cfg),
        lambda: _synth_modality(modality, label, n_synth_traces),
        cfg=cfg)
    if modality == "logs":
        # keep real summaries when only the line payloads were stubs
        syn_batch, syn_sum = syn
        real_sum = value[1]
        return (syn_batch, real_sum if real_sum else syn_sum), True
    return syn, True


def load_experiment(name: str, testbed: Optional[str] = None,
                    cfg: Optional[Config] = None,
                    modalities: Optional[List[str]] = None,
                    n_synth_traces: int = 200) -> Experiment:
    """Load one experiment's modalities; synth-fill anything unavailable."""
    cfg = cfg or get_config()
    label = labels_mod.label_for(name)
    if label is None:
        raise KeyError(f"unknown experiment: {name}")
    testbed = testbed or label.testbed
    modalities = modalities or list(MODALITIES)
    dirs = {e.name: e for e in discover(testbed, cfg)}.get(label.experiment)
    exp = Experiment(name=label.experiment, testbed=testbed)
    any_synth = False

    d = dirs.dirs if dirs else {}
    for modality in modalities:
        value, syn = _load_modality(modality, label, testbed,
                                    d.get(modality), n_synth_traces, cfg)
        any_synth = any_synth or syn
        if modality == "traces":
            exp.spans = value
        elif modality == "metrics":
            exp.metrics = value
        elif modality == "logs":
            exp.logs, exp.log_summaries = value
        elif modality == "api":
            exp.api = value
        elif modality == "coverage":
            exp.coverage = value

    exp.synthetic = any_synth
    return exp


def _load_experiment_task(name: str, testbed: str, cfg: Config,
                          modalities: Optional[List[str]],
                          n_synth_traces: int):
    """Top-level (picklable) worker entry for the process-pool loader.

    Ships the worker's cache-counter snapshot home with the Experiment —
    the spawn child's module globals never propagate back on their own,
    and an all-zero report would defeat the hit/miss honesty signal."""
    cache.reset_stats()
    exp = load_experiment(name, testbed, cfg, modalities, n_synth_traces)
    return exp, cache.stats().to_dict()


def load_corpus(testbed: str, cfg: Optional[Config] = None,
                modalities: Optional[List[str]] = None,
                n_synth_traces: int = 200,
                workers: Optional[int] = None) -> List[Experiment]:
    """All 13 experiments of a testbed (12 faults + normal).

    ``workers`` (default ``Config.ingest_workers``; 0/1 = serial) fans the
    per-experiment loads across a spawn-context process pool — spawn, not
    fork, because the parent may have an initialized JAX backend and the
    loaders only need numpy.  Cache writes from workers are safe: entries
    publish atomically and collisions are identical by construction.
    """
    cfg = cfg or get_config()
    names = [l.experiment for l in labels_mod.labels_for_testbed(testbed)]
    if workers is None:
        workers = cfg.ingest_workers
    if workers and workers > 1 and len(names) > 1:
        import multiprocessing
        import time as _time
        from concurrent.futures import ProcessPoolExecutor

        from anomod import obs
        depth = obs.gauge("anomod_ingest_pool_pending")
        wall = obs.histogram("anomod_ingest_pool_experiment_seconds")
        ctx = multiprocessing.get_context("spawn")
        with ProcessPoolExecutor(max_workers=min(workers, len(names)),
                                 mp_context=ctx) as pool:
            t0 = _time.perf_counter()

            def done(_f):
                # submit→result wall + queue depth recorded at COMPLETION
                # (executor callback thread), not at the in-order drain
                # below — a fast experiment finishing behind a slow one
                # must not inherit the slow one's wall
                wall.observe(_time.perf_counter() - t0)
                depth.dec()

            futs = []
            for n in names:
                depth.inc()        # before submit: a dec can never race
                f = pool.submit(_load_experiment_task, n, testbed, cfg,
                                modalities, n_synth_traces)
                f.add_done_callback(done)
                futs.append(f)
            out = []
            for f in futs:
                exp, worker_stats = f.result()
                cache.merge_stats(worker_stats)
                out.append(exp)
            return out
    return [load_experiment(n, testbed, cfg, modalities, n_synth_traces)
            for n in names]


# ---------------------------------------------------------------------------
# Bench ingest helpers — the corpus bench.py replays, read through the cache
# at the CONCATENATED level: one entry per (testbed, n_traces), so the warm
# path is a single bulk columnar read with no per-label re-intern concat.
# ---------------------------------------------------------------------------

def bench_corpus_key_parts(testbed: str, n_traces: int,
                           cfg: Optional[Config] = None) -> dict:
    cfg = cfg or get_config()
    return {
        "source": "synth-corpus",
        "synth_version": synth.SYNTH_VERSION,
        "testbed": testbed,
        "n_traces": n_traces,
        "experiments": [l.experiment
                        for l in labels_mod.labels_for_testbed(testbed)],
    }


def load_bench_corpus(testbed: str, n_traces: int,
                      cfg: Optional[Config] = None):
    """The concatenated bench replay corpus, read through the cache.

    Returns ``(SpanBatch, info)`` where ``info`` carries the honest
    cold-vs-warm accounting: ``parse_s`` is the recorded cold
    generate+concat wall (measured now on a miss, read from the entry on a
    hit), so the cold number survives even when the batch came warm.
    """
    cfg = cfg or get_config()
    import time as _time
    from anomod.schemas import concat_span_batches

    def compute():
        return concat_span_batches(
            [synth.generate_spans(l, n_traces=n_traces)
             for l in labels_mod.labels_for_testbed(testbed)])

    t0 = _time.perf_counter()
    batch, hit, meta = cache.cached(
        "spans", bench_corpus_key_parts(testbed, n_traces, cfg),
        compute, cfg=cfg)
    info = {"cache_hit": hit,
            "parse_s": float(meta.get("parse_s", 0.0)),
            "load_s": _time.perf_counter() - t0,
            "n_experiments": len(labels_mod.labels_for_testbed(testbed))}
    return batch, info


def bench_cache_status(testbed: str, n_traces: int,
                       cfg: Optional[Config] = None) -> Tuple[int, int]:
    """(present, total) bench-corpus cache entries — the pre-bench gate's
    cold/warm check, without loading anything."""
    cfg = cfg or get_config()
    root = cache.cache_root(cfg)
    if root is None:
        return 0, 1
    key = cache.full_key("spans",
                         bench_corpus_key_parts(testbed, n_traces, cfg))
    return (1 if cache.entry_paths(root, key)[0].is_file() else 0), 1
