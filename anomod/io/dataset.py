"""Dataset discovery + experiment loading with synthetic fallback.

Archive layout (SURVEY.md §2.3 / L7):
  SN_data/{log,metric,trace,coverage}_data + api_responses, experiment dirs
  named ``<Exp>_<YYYYMMDD_HHMMSS>_<modality>_<...>`` (collect_all_data.sh:207-211).
  TT_data/{log,metric,trace,api_responses,coverage_data,coverage_report}
  with dirs named ``<Lv_*|Normal_case>_<ISO8601>_em`` (T-Dataset/README.md:9-17).

Every payload that is a git-LFS pointer stub falls back to the deterministic
synthetic generator (config.synth_on_lfs), keeping the full 2x13-experiment
corpus loadable from the shipped checkout.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Optional

from anomod import labels as labels_mod
from anomod import synth
from anomod.config import Config, get_config
from anomod.io import api as api_io
from anomod.io import coverage as cov_io
from anomod.io import logs as logs_io
from anomod.io import metrics as met_io
from anomod.io import sn_traces, tt_traces
from anomod.schemas import Experiment

_SN_MODALITY_DIRS = {
    "traces": "trace_data", "metrics": "metric_data", "logs": "log_data",
    "api": "api_responses", "coverage": "coverage_data",
}
_TT_MODALITY_DIRS = {
    "traces": "trace_data", "metrics": "metric_data", "logs": "log_data",
    "api": "api_responses", "coverage": "coverage_report",
}


@dataclasses.dataclass
class ExperimentDirs:
    name: str                      # canonical experiment name
    testbed: str
    dirs: Dict[str, Path]          # modality -> experiment dir


def discover(testbed: str, cfg: Optional[Config] = None) -> List[ExperimentDirs]:
    """Walk the archive tree, grouping modality dirs by canonical experiment."""
    cfg = cfg or get_config()
    root = cfg.sn_data if testbed == "SN" else cfg.tt_data
    modality_dirs = _SN_MODALITY_DIRS if testbed == "SN" else _TT_MODALITY_DIRS
    found: Dict[str, ExperimentDirs] = {}
    for modality, sub in modality_dirs.items():
        base = root / sub
        if not base.is_dir():
            continue
        for d in sorted(base.iterdir()):
            if not d.is_dir():
                continue
            canon = labels_mod.canonical_experiment(d.name)
            if labels_mod.label_for(canon) is None:
                continue
            ed = found.setdefault(canon, ExperimentDirs(canon, testbed, {}))
            ed.dirs.setdefault(modality, d)
    return list(found.values())


def load_experiment(name: str, testbed: Optional[str] = None,
                    cfg: Optional[Config] = None,
                    modalities: Optional[List[str]] = None,
                    n_synth_traces: int = 200) -> Experiment:
    """Load one experiment's modalities; synth-fill anything unavailable."""
    cfg = cfg or get_config()
    label = labels_mod.label_for(name)
    if label is None:
        raise KeyError(f"unknown experiment: {name}")
    testbed = testbed or label.testbed
    modalities = modalities or ["traces", "metrics", "logs", "api", "coverage"]
    dirs = {e.name: e for e in discover(testbed, cfg)}.get(label.experiment)
    exp = Experiment(name=label.experiment, testbed=testbed)
    any_synth = False

    d = dirs.dirs if dirs else {}
    if "traces" in modalities:
        if "traces" in d:
            if testbed == "TT":
                art = tt_traces.find_trace_artifact(d["traces"])
                exp.spans = tt_traces.load_skywalking_json(art) if art else None
            else:
                art = sn_traces.find_trace_artifact(d["traces"])
                if art and art.suffix == ".json":
                    exp.spans = sn_traces.load_jaeger_json(art)
                elif art:
                    exp.spans = sn_traces.load_jaeger_csv(art)
        if exp.spans is None and cfg.synth_on_lfs:
            exp.spans = synth.generate_spans(label, n_traces=n_synth_traces)
            any_synth = True

    if "metrics" in modalities:
        if "metrics" in d:
            if testbed == "TT":
                art = met_io.find_tt_metric_artifact(d["metrics"])
                exp.metrics = met_io.load_tt_metric_csv(art) if art else None
            else:
                exp.metrics = met_io.load_sn_metric_dir(d["metrics"])
        if exp.metrics is None and cfg.synth_on_lfs:
            exp.metrics = synth.generate_metrics(label)
            any_synth = True

    if "logs" in modalities:
        if "logs" in d:
            loader = logs_io.load_tt_log_dir if testbed == "TT" else logs_io.load_sn_log_dir
            exp.logs, exp.log_summaries = loader(d["logs"])
        if exp.logs is None and cfg.synth_on_lfs:
            exp.logs, syn_sum = synth.generate_logs(label)
            if not exp.log_summaries:
                exp.log_summaries = syn_sum
            any_synth = True

    if "api" in modalities:
        if "api" in d:
            art = api_io.find_api_artifact(d["api"])
            exp.api = api_io.load_api_jsonl(art) if art else None
        if exp.api is None and cfg.synth_on_lfs:
            exp.api = synth.generate_api(label)
            any_synth = True

    if "coverage" in modalities:
        if "coverage" in d:
            loader = (cov_io.load_tt_coverage_report if testbed == "TT"
                      else cov_io.load_sn_coverage_dir)
            exp.coverage = loader(d["coverage"])
        if exp.coverage is None and cfg.synth_on_lfs:
            exp.coverage = synth.generate_coverage(label)
            any_synth = True

    exp.synthetic = any_synth
    return exp


def load_corpus(testbed: str, cfg: Optional[Config] = None,
                modalities: Optional[List[str]] = None,
                n_synth_traces: int = 200) -> List[Experiment]:
    """All 13 experiments of a testbed (12 faults + normal)."""
    return [load_experiment(l.experiment, testbed, cfg, modalities, n_synth_traces)
            for l in labels_mod.labels_for_testbed(testbed)]
