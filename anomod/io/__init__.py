"""Typed loaders for the seven reference artifact families (SN+TT × modalities)."""

from anomod.io.lfs import is_lfs_pointer, read_text_or_none

__all__ = ["is_lfs_pointer", "read_text_or_none"]
