"""TT / SkyWalking trace JSON loader → SpanBatch.

Consumes the collector artifact schema (trace_collector.py:552-584):
``{"metadata": {...}, "traces": [{"trace_id", "span_count",
"services_involved", "root_span_node_ids", "spans": [span_dict...]}]}``
with span dicts per the ``to_dict`` contract (trace_collector.py:86-123):
``node_id="segment:span"``, ``parent_span_id`` (same-segment) and cross-segment
``refs[{parentSegmentId, parentSpanId}]`` — re-implemented here as vectorized
columnar resolution (the reference builds the graph per-span in Python,
trace_collector.py:401-481).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from anomod.io.lfs import is_lfs_pointer
from anomod.schemas import (KIND_ENTRY, KIND_EXIT, KIND_LOCAL, SpanBatch,
                            empty_span_batch)

#: Ingest-cache key component (anomod.io.cache): bump when this module's
#: parsing semantics change, invalidating exactly the TT trace entries.
LOADER_VERSION = 1

_KIND = {"Entry": KIND_ENTRY, "Exit": KIND_EXIT, "Local": KIND_LOCAL}


def load_skywalking_json(path: Path) -> Optional[SpanBatch]:
    """Load one collector JSON artifact; None if missing/LFS stub."""
    path = Path(path)
    if not path.is_file() or is_lfs_pointer(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    return spans_from_skywalking(doc)


def spans_from_skywalking(doc: dict) -> SpanBatch:
    traces = doc.get("traces", [])
    if not traces:
        return empty_span_batch()

    services: Dict[str, int] = {}
    endpoints: Dict[str, int] = {}
    trace_ids: Dict[str, int] = {}

    # First pass: flatten spans, record (segment_id, span_id) -> row.
    n = sum(len(t.get("spans", [])) for t in traces)
    trace_c = np.zeros(n, np.int32)
    service_c = np.zeros(n, np.int32)
    endpoint_c = np.zeros(n, np.int32)
    start_c = np.zeros(n, np.int64)
    dur_c = np.zeros(n, np.int64)
    err_c = np.zeros(n, np.bool_)
    status_c = np.zeros(n, np.int16)
    kind_c = np.zeros(n, np.int8)
    parent_c = np.full(n, -1, np.int32)

    row_of: Dict[tuple, int] = {}
    pending: List[tuple] = []  # (row, parent_segment, parent_span)

    r = 0
    for t in traces:
        tid = t.get("trace_id") or (t.get("summary", {}).get("trace_ids") or [""])[0]
        t_idx = trace_ids.setdefault(tid, len(trace_ids))
        for sp in t.get("spans", []):
            seg = sp.get("segment_id", "")
            sid = int(sp.get("span_id", 0))
            row_of[(seg, sid)] = r
            trace_c[r] = t_idx
            service_c[r] = services.setdefault(sp.get("service_code", ""), len(services))
            endpoint_c[r] = endpoints.setdefault(sp.get("endpoint_name") or "", len(endpoints))
            start_ms = int(sp.get("start_timestamp_ms", 0))
            end_ms = int(sp.get("end_timestamp_ms", start_ms))
            start_c[r] = start_ms * 1000
            dur_c[r] = max(0, end_ms - start_ms) * 1000
            err_c[r] = bool(sp.get("is_error", False))
            tags = sp.get("tags_map") or {}
            try:
                status_c[r] = int(tags.get("http.status_code", 0) or 0)
            except (TypeError, ValueError):
                status_c[r] = 0
            kind_c[r] = _KIND.get(sp.get("type", "Local"), KIND_LOCAL)
            # parent: same-segment parent_span_id >= 0, else refs[0]
            psid = sp.get("parent_span_id", -1)
            if psid is not None and int(psid) >= 0:
                pending.append((r, seg, int(psid)))
            else:
                refs = sp.get("refs") or []
                if refs:
                    ref = refs[0]
                    pending.append((r, ref.get("parentSegmentId", ""),
                                    int(ref.get("parentSpanId", -1))))
            r += 1

    for row, pseg, psid in pending:
        parent = row_of.get((pseg, psid), -1)
        parent_c[row] = parent

    return SpanBatch(
        trace=trace_c, parent=parent_c, service=service_c, endpoint=endpoint_c,
        start_us=start_c, duration_us=dur_c, is_error=err_c, status=status_c,
        kind=kind_c,
        services=tuple(services), endpoints=tuple(endpoints),
        trace_ids=tuple(trace_ids),
    ).validate()


def find_trace_artifact(exp_dir: Path) -> Optional[Path]:
    """TT layout: <exp>/<exp>_skywalking_traces_<ts>.json (T-Dataset/README.md:13)."""
    cands = sorted(Path(exp_dir).glob("*skywalking_traces*.json"))
    return cands[-1] if cands else None
