"""Service-dependency graph construction from span batches.

Re-implements, as vectorized columnar ops, what the reference derives span-by-
span in Python: parent resolution and graph building
(trace_collector.py:401-481 BFS; jaeger_to_csv.py:35-38 CHILD_OF refs).  By the
time spans reach this module they are already a SpanBatch with resolved
``parent`` row indices (the loaders handle both conventions), so everything
here is O(n) numpy on fixed-dtype columns — the same code path the TPU replay
uses for feature extraction.

Outputs:
  - ``ServiceGraph``: dense service×service edge matrix + padded CSR
    (TPU-friendly fixed shapes for GNN message passing).
  - per-service / per-edge aggregates (count, error rate, latency stats).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import numpy as np

from anomod.schemas import SpanBatch


class ServiceGraph(NamedTuple):
    """Service DAG with padded-CSR adjacency (static shapes for XLA)."""

    services: Tuple[str, ...]
    # dense [S, S] call-count matrix: A[i, j] = #spans where i calls j
    adj_counts: np.ndarray          # int64
    # per-edge latency/error aggregates aligned with edge list
    edge_src: np.ndarray            # int32 [E]
    edge_dst: np.ndarray            # int32 [E]
    edge_count: np.ndarray          # int64 [E]
    edge_err: np.ndarray            # int64 [E]
    edge_lat_sum_us: np.ndarray     # float64 [E]
    # padded CSR over the fixed service set: neighbors[i, k] = k-th callee
    neighbors: np.ndarray           # int32 [S, Dmax] (padded with -1)
    neighbor_mask: np.ndarray       # bool  [S, Dmax]

    @property
    def n_services(self) -> int:
        return len(self.services)

    @property
    def n_edges(self) -> int:
        return int(self.edge_src.shape[0])


def depths(batch: SpanBatch) -> np.ndarray:
    """Span depth in its trace (root=0), replacing the reference's BFS
    (trace_collector.py:461-481) with pointer-jumping over the parent column —
    O(n log d) and fully vectorized."""
    n = batch.n_spans
    d = np.zeros(n, np.int32)
    cur = batch.parent.copy()
    while (cur >= 0).any():
        live = cur >= 0
        d[live] += 1
        cur = np.where(live, batch.parent[np.clip(cur, 0, None)], -1)
    return d


def service_edges(batch: SpanBatch) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(src_service, dst_service, span_row) for every cross-service call.

    A call edge exists where a span's parent lives in a different service
    (covers both SkyWalking Exit→Entry pairs and Jaeger CHILD_OF chains).
    """
    has_parent = batch.parent >= 0
    child = np.flatnonzero(has_parent)
    par = batch.parent[child]
    src = batch.service[par]
    dst = batch.service[child]
    cross = src != dst
    return src[cross], dst[cross], child[cross]


def build_service_graph(batch: SpanBatch,
                        services: Optional[Tuple[str, ...]] = None,
                        max_degree: Optional[int] = None) -> ServiceGraph:
    """Build the service DAG.  ``services`` pins the node set (and ordering) so
    graphs from different experiments share shapes; defaults to batch table."""
    if services is None:
        services = batch.services
    S = len(services)
    # remap batch-local service ids into the pinned table
    remap = np.full(len(batch.services), -1, np.int32)
    svc_index = {s: i for i, s in enumerate(services)}
    for i, s in enumerate(batch.services):
        remap[i] = svc_index.get(s, -1)

    src_l, dst_l, child_rows = service_edges(batch)
    src = remap[src_l]
    dst = remap[dst_l]
    keep = (src >= 0) & (dst >= 0)
    src, dst, child_rows = src[keep], dst[keep], child_rows[keep]

    flat = src.astype(np.int64) * S + dst
    adj = np.zeros(S * S, np.int64)
    np.add.at(adj, flat, 1)
    err = np.zeros(S * S, np.int64)
    np.add.at(err, flat, batch.is_error[child_rows].astype(np.int64))
    lat = np.zeros(S * S, np.float64)
    np.add.at(lat, flat, batch.duration_us[child_rows].astype(np.float64))

    eflat = np.flatnonzero(adj)
    edge_src = (eflat // S).astype(np.int32)
    edge_dst = (eflat % S).astype(np.int32)

    # padded CSR
    deg = np.zeros(S, np.int64)
    np.add.at(deg, edge_src, 1)
    dmax = int(max_degree or max(int(deg.max(initial=0)), 1))
    neighbors = np.full((S, dmax), -1, np.int32)
    mask = np.zeros((S, dmax), np.bool_)
    slot = np.zeros(S, np.int64)
    for e in range(eflat.shape[0]):
        s = edge_src[e]
        k = slot[s]
        if k < dmax:
            neighbors[s, k] = edge_dst[e]
            mask[s, k] = True
            slot[s] += 1

    return ServiceGraph(
        services=tuple(services),
        adj_counts=adj.reshape(S, S),
        edge_src=edge_src, edge_dst=edge_dst,
        edge_count=adj[eflat], edge_err=err[eflat],
        edge_lat_sum_us=lat[eflat],
        neighbors=neighbors, neighbor_mask=mask,
    )


# ---------------------------------------------------------------------------
# Per-service span aggregates — the feature vector the detectors consume.
# ---------------------------------------------------------------------------

class ServiceStats(NamedTuple):
    services: Tuple[str, ...]
    count: np.ndarray        # int64 [S]
    err_count: np.ndarray    # int64 [S]
    err_rate: np.ndarray     # float64 [S]
    lat_mean_us: np.ndarray  # float64 [S]
    lat_p50_us: np.ndarray   # float64 [S]
    lat_p95_us: np.ndarray   # float64 [S]
    lat_p99_us: np.ndarray   # float64 [S]


def service_stats(batch: SpanBatch,
                  services: Optional[Tuple[str, ...]] = None) -> ServiceStats:
    """Count / error-rate / latency percentiles per service.

    Percentiles are computed with one global sort + per-service segment
    indexing (the same sort+segment pattern the TPU kernels use), not a
    Python loop over services.
    """
    if services is None:
        services = batch.services
    S = len(services)
    svc_index = {s: i for i, s in enumerate(services)}
    remap = np.array([svc_index.get(s, -1) for s in batch.services] or [-1],
                     np.int32)
    svc = remap[batch.service] if batch.n_spans else np.zeros(0, np.int32)
    keep = svc >= 0
    svc = svc[keep]
    dur = batch.duration_us[keep].astype(np.float64)
    err = batch.is_error[keep]

    count = np.zeros(S, np.int64)
    np.add.at(count, svc, 1)
    err_count = np.zeros(S, np.int64)
    np.add.at(err_count, svc, err.astype(np.int64))
    lat_sum = np.zeros(S, np.float64)
    np.add.at(lat_sum, svc, dur)

    # segment-sorted percentiles
    p50 = np.zeros(S); p95 = np.zeros(S); p99 = np.zeros(S)
    if svc.shape[0]:
        order = np.lexsort((dur, svc))
        svc_s, dur_s = svc[order], dur[order]
        starts = np.searchsorted(svc_s, np.arange(S))
        ends = np.searchsorted(svc_s, np.arange(S) + 1)
        seg_len = ends - starts
        for q, out in ((0.50, p50), (0.95, p95), (0.99, p99)):
            idx = starts + np.clip((seg_len * q).astype(np.int64),
                                   0, np.maximum(seg_len - 1, 0))
            vals = dur_s[np.clip(idx, 0, max(dur_s.shape[0] - 1, 0))] \
                if dur_s.shape[0] else np.zeros(S)
            out[:] = np.where(seg_len > 0, vals, 0.0)

    with np.errstate(invalid="ignore", divide="ignore"):
        err_rate = np.where(count > 0, err_count / np.maximum(count, 1), 0.0)
        lat_mean = np.where(count > 0, lat_sum / np.maximum(count, 1), 0.0)

    return ServiceStats(services=tuple(services), count=count,
                        err_count=err_count, err_rate=err_rate,
                        lat_mean_us=lat_mean, lat_p50_us=p50,
                        lat_p95_us=p95, lat_p99_us=p99)
