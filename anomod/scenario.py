"""TT scenario driver — the hand-written user-journey workload, re-designed
as a deterministic request-program generator over the synthetic SUT.

The reference drives a live Train-Ticket cluster with ~25 atomic HTTP
primitives (train-ticket-auto-query/atomic_queries.py: `_login`:31,
`_query_high_speed_ticket`:71, `_query_orders`:256, `_pay_one_order`:370,
`_cancel_one_order`:389, `_collect_one_order`:403, `_enter_station`:415,
`_rebook_ticket`:499, `_put_consign`:329, admin queries :475-525) chained
into service-category flows plus a condensed booking flow
(test_all_services.py: core :127-196, auxiliary :198-265, admin :267-297,
extended :299-384, complete flow :386-427), with a token refresh every 10
iterations (:436-441).

Here the same flows are *programs*: each primitive emits a
:class:`RequestSpec` (method, path, owning service); the
:class:`ScenarioDriver` sequences them with the same data dependencies
(query orders → pay first unpaid → collect/enter first paid → rebook) over an
explicit order state machine; and the :class:`SyntheticGateway` executes the
program against the synthetic SUT — routing by path the way the real gateway
does, applying any active :class:`~anomod.chaos.ChaosController` faults to
latency/error, and accumulating a schema-exact
:class:`~anomod.schemas.ApiBatch`.  Execution is seeded and fully
reproducible, so the driver doubles as a traffic model for the generator and
a workload for replay benchmarks.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod.schemas import ApiBatch

# ---------------------------------------------------------------------------
# Routing: path prefix → owning ts-* service (the gateway's dispatch table).
# Endpoints from atomic_queries.py / test_all_services.py cited above.
# ---------------------------------------------------------------------------

PATH_SERVICE: Tuple[Tuple[str, str], ...] = (
    ("/api/v1/users/login", "ts-user-service"),
    ("/api/v1/auth", "ts-auth-service"),
    ("/api/v1/travelservice", "ts-travel-service"),
    ("/api/v1/travel2service", "ts-travel2-service"),
    ("/api/v1/travelplanservice", "ts-travel-plan-service"),
    ("/api/v1/routeplanservice", "ts-route-plan-service"),
    ("/api/v1/routeservice", "ts-route-service"),
    ("/api/v1/assuranceservice", "ts-assurance-service"),
    ("/api/v1/foodservice", "ts-food-service"),
    ("/api/v1/stationfoodservice", "ts-station-food-service"),
    ("/api/v1/trainfoodservice", "ts-train-food-service"),
    ("/api/v1/fooddeliveryservice", "ts-food-delivery-service"),
    ("/api/v1/contactservice", "ts-contacts-service"),
    ("/api/v1/orderOtherService", "ts-order-other-service"),
    ("/api/v1/orderservice", "ts-order-service"),
    ("/api/v1/preserveservice", "ts-preserve-service"),
    ("/api/v1/preserveotherservice", "ts-preserve-other-service"),
    ("/api/v1/securityservice", "ts-security-service"),
    ("/api/v1/inside_pay_service", "ts-inside-payment-service"),
    ("/api/v1/paymentservice", "ts-payment-service"),
    ("/api/v1/cancelservice", "ts-cancel-service"),
    ("/api/v1/executeservice", "ts-execute-service"),
    ("/api/v1/rebookservice", "ts-rebook-service"),
    ("/api/v1/consignservice", "ts-consign-service"),
    ("/api/v1/consignpriceservice", "ts-consign-price-service"),
    ("/api/v1/deliveryservice", "ts-delivery-service"),
    ("/api/v1/notificationservice", "ts-notification-service"),
    ("/api/v1/newsservice", "ts-news-service"),
    ("/api/v1/voucherservice", "ts-voucher-service"),
    ("/api/v1/waitorderservice", "ts-wait-order-service"),
    ("/api/v1/basicservice", "ts-basic-service"),
    ("/api/v1/configservice", "ts-config-service"),
    ("/api/v1/stationservice", "ts-station-service"),
    ("/api/v1/trainservice", "ts-train-service"),
    ("/api/v1/adminbasicservice", "ts-admin-basic-info-service"),
    ("/api/v1/admintravelservice", "ts-admin-travel-service"),
    ("/api/v1/adminorderservice", "ts-admin-order-service"),
    ("/api/v1/adminrouteservice", "ts-admin-route-service"),
    ("/api/v1/adminuserservice", "ts-admin-user-service"),
    ("/api/v1/avatarservice", "ts-avatar-service"),
    ("/api/v1/verifycode", "ts-verification-code-service"),
)


def route(path: str) -> str:
    """Owning service for a request path (longest-prefix wins)."""
    best = ""
    svc = "ts-gateway-service"
    for prefix, service in PATH_SERVICE:
        if path.startswith(prefix) and len(prefix) > len(best):
            best, svc = prefix, service
    return svc


@dataclasses.dataclass(frozen=True)
class RequestSpec:
    method: str
    path: str            # instantiated path
    template: str        # canonical path template (stable endpoint vocab)
    flow: str = ""       # which scenario flow emitted it
    owner: str = ""      # explicit owning service (SN specs); "" = TT route
    body: Optional[str] = None   # synthesized request body (wrk2 model)

    @property
    def service(self) -> str:
        return self.owner or route(self.path)

    @property
    def endpoint(self) -> str:
        return f"{self.method} {self.template}"


def _spec(method: str, path: str, template: Optional[str] = None,
          flow: str = "") -> RequestSpec:
    return RequestSpec(method, path, template or path, flow)


# ---------------------------------------------------------------------------
# Atomic primitives (atomic_queries.py equivalents, citations above).
# Each returns the RequestSpec(s) the reference primitive would issue.
# ---------------------------------------------------------------------------

def login() -> RequestSpec:
    return _spec("POST", "/api/v1/users/login")


def query_high_speed_ticket() -> RequestSpec:
    return _spec("POST", "/api/v1/travelservice/trips/left")


def query_high_speed_ticket_parallel() -> RequestSpec:
    return _spec("POST", "/api/v1/travelservice/trips/left_parallel")


def query_normal_ticket() -> RequestSpec:
    return _spec("POST", "/api/v1/travel2service/trips/left")


def query_advanced_ticket(plan_type: str) -> RequestSpec:
    return _spec("POST", f"/api/v1/travelplanservice/travelPlan/{plan_type}",
                 "/api/v1/travelplanservice/travelPlan/{type}")


def query_assurances() -> RequestSpec:
    return _spec("GET", "/api/v1/assuranceservice/assurances/types")


def query_food(date: str = "2026-01-05", src: str = "Shang Hai",
               dst: str = "Su Zhou", train: str = "D1345") -> RequestSpec:
    return _spec("GET", f"/api/v1/foodservice/foods/{date}/{src}/{dst}/{train}",
                 "/api/v1/foodservice/foods/{date}/{from}/{to}/{train}")


def query_contacts(account_id: str = "uid-0") -> RequestSpec:
    return _spec("GET", f"/api/v1/contactservice/contacts/account/{account_id}",
                 "/api/v1/contactservice/contacts/account/{id}")


def query_orders(other: bool = False) -> RequestSpec:
    if other:
        return _spec("POST", "/api/v1/orderOtherService/orderOther/refresh")
    return _spec("POST", "/api/v1/orderservice/order/refresh")


def put_consign() -> RequestSpec:
    return _spec("PUT", "/api/v1/consignservice/consigns")


def query_route(route_id: str = "route-0") -> RequestSpec:
    return _spec("GET", f"/api/v1/routeservice/routes/{route_id}",
                 "/api/v1/routeservice/routes/{id}")


def preserve() -> RequestSpec:
    """Create a booking — the path the Lv_S_HTTPABORT fault targets
    (Lv_S_HTTPABORT_preserve.yaml:23: /api/v1/preserveservice/*)."""
    return _spec("POST", "/api/v1/preserveservice/preserve")


def pay_one_order(order_id: str) -> RequestSpec:
    return _spec("POST", "/api/v1/inside_pay_service/inside_payment")


def cancel_one_order(order_id: str, uuid: str = "uid-0") -> RequestSpec:
    return _spec("GET", f"/api/v1/cancelservice/cancel/{order_id}/{uuid}",
                 "/api/v1/cancelservice/cancel/{orderId}/{uuid}")


def collect_one_order(order_id: str) -> RequestSpec:
    return _spec("GET", f"/api/v1/executeservice/execute/collected/{order_id}",
                 "/api/v1/executeservice/execute/collected/{orderId}")


def enter_station(order_id: str) -> RequestSpec:
    return _spec("GET", f"/api/v1/executeservice/execute/execute/{order_id}",
                 "/api/v1/executeservice/execute/execute/{orderId}")


def rebook_ticket(old_order_id: str) -> RequestSpec:
    return _spec("POST", "/api/v1/rebookservice/rebook")


def query_cheapest() -> RequestSpec:
    return query_advanced_ticket("cheapest")


def query_min_station() -> RequestSpec:
    return query_advanced_ticket("minStation")


def query_quickest() -> RequestSpec:
    return query_advanced_ticket("quickest")


def query_admin_basic_price() -> RequestSpec:
    return _spec("GET", "/api/v1/adminbasicservice/adminbasic/prices")


def query_admin_basic_config() -> RequestSpec:
    return _spec("GET", "/api/v1/adminbasicservice/adminbasic/configs")


def query_admin_travel() -> RequestSpec:
    return _spec("GET", "/api/v1/admintravelservice/admintravel")


# Extended coverage endpoints (test_all_services.py:299-384): one GET per
# optional service so every microservice appears in the traffic at least once.
EXTENDED_ENDPOINTS: Tuple[Tuple[str, str], ...] = (
    ("POST", "/api/v1/auth/login"),
    ("GET", "/api/v1/avatarservice/avatar/{id}"),
    ("GET", "/api/v1/basicservice/basic/travel"),
    ("GET", "/api/v1/basicservice/basic/stations"),
    ("GET", "/api/v1/configservice/configs"),
    ("GET", "/api/v1/deliveryservice/delivery"),
    ("GET", "/api/v1/fooddeliveryservice/fooddelivery"),
    ("GET", "/api/v1/newsservice/news"),
    ("GET", "/api/v1/paymentservice/payment"),
    ("GET", "/api/v1/routeplanservice/routePlan"),
    ("GET", "/api/v1/stationfoodservice/stationfood"),
    ("GET", "/api/v1/ticketofficeservice/ticketoffice"),
    ("GET", "/api/v1/trainfoodservice/trainfood"),
    ("GET", "/api/v1/voucherservice/vouchers"),
    ("GET", "/api/v1/waitorderservice/waitorder"),
    ("GET", "/api/v1/consignpriceservice/consignprice"),
)


# ---------------------------------------------------------------------------
# Driver: the flow state machine
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Order:
    order_id: str
    trip_id: str
    paid: bool = False


class ScenarioDriver:
    """Sequences the reference's five flows with real data dependencies.

    Orders move unpaid → paid → collected/used exactly as the chained
    primitives in test_all_services.py consume them (each step's output feeds
    the next: `_query_orders → _pay_one_order(orders[0])` :169-171).
    """

    def __init__(self, seed: int = 0) -> None:
        self._orders: List[_Order] = []
        self._n_created = 0
        self._seed = seed
        self._iteration = 0

    # -- order state machine ------------------------------------------------
    def _create_order(self) -> _Order:
        self._n_created += 1
        o = _Order(f"order-{self._seed}-{self._n_created}",
                   f"D{1000 + self._n_created % 500}")
        self._orders.append(o)
        return o

    def _first(self, paid: Optional[bool] = None) -> Optional[_Order]:
        for o in self._orders:
            if paid is None or o.paid == paid:
                return o
        return None

    # -- flows --------------------------------------------------------------
    def core_business_flow(self) -> List[RequestSpec]:
        """test_all_services.py:127-196."""
        out = [dataclasses.replace(login(), flow="core")]
        for _ in range(3):
            out.append(dataclasses.replace(query_high_speed_ticket(), flow="core"))
        for _ in range(2):
            out.append(dataclasses.replace(query_normal_ticket(), flow="core"))
        for plan in ("cheapest", "quickest", "minStation"):
            out.append(dataclasses.replace(query_advanced_ticket(plan), flow="core"))
        out.append(dataclasses.replace(query_orders(other=False), flow="core"))
        out.append(dataclasses.replace(query_orders(other=True), flow="core"))
        # booking: the reference leaves preserve as a placeholder; we book so
        # downstream pay/cancel/execute steps have orders to consume.
        out.append(dataclasses.replace(preserve(), flow="core"))
        self._create_order()
        out.append(dataclasses.replace(query_orders(), flow="core"))
        unpaid = self._first(paid=False)
        if unpaid is not None:
            out.append(dataclasses.replace(pay_one_order(unpaid.order_id), flow="core"))
            unpaid.paid = True
        victim = self._first()
        if victim is not None:
            out.append(dataclasses.replace(
                cancel_one_order(victim.order_id), flow="core"))
            self._orders.remove(victim)
        out.append(dataclasses.replace(preserve(), flow="core"))
        o = self._create_order()
        o.paid = True
        paid = self._first(paid=True)
        if paid is not None:
            out.append(dataclasses.replace(collect_one_order(paid.order_id), flow="core"))
            out.append(dataclasses.replace(enter_station(paid.order_id), flow="core"))
            out.append(dataclasses.replace(rebook_ticket(paid.order_id), flow="core"))
            self._orders.remove(paid)   # ticket used; keep state bounded
        return out

    def auxiliary_flow(self) -> List[RequestSpec]:
        """test_all_services.py:198-265 — contacts/assurance/food/consign/
        security/station/train/price/notification."""
        specs = [
            query_contacts(), query_assurances(), query_food(), put_consign(),
            query_route(),
            _spec("GET", "/api/v1/securityservice/securityConfigs"),
            _spec("GET", "/api/v1/stationservice/stations"),
            _spec("GET", "/api/v1/trainservice/trains"),
            _spec("POST", "/api/v1/notificationservice/notification/preserve_success"),
        ]
        return [dataclasses.replace(s, flow="auxiliary") for s in specs]

    def admin_flow(self) -> List[RequestSpec]:
        """test_all_services.py:267-297."""
        specs = [
            query_admin_basic_price(), query_admin_basic_config(),
            query_admin_travel(),
            _spec("GET", "/api/v1/adminorderservice/adminorder"),
            _spec("GET", "/api/v1/adminrouteservice/adminroute"),
            _spec("GET", "/api/v1/adminuserservice/users"),
        ]
        return [dataclasses.replace(s, flow="admin") for s in specs]

    def extended_flow(self) -> List[RequestSpec]:
        """test_all_services.py:299-384."""
        return [_spec(m, p.replace("{id}", "uid-0"), p, flow="extended")
                for m, p in EXTENDED_ENDPOINTS]

    def complete_business_flow(self) -> List[RequestSpec]:
        """The condensed booking journey (test_all_services.py:386-427):
        search → aux info → reserve → orders → pay → collect → enter."""
        out = [dataclasses.replace(query_high_speed_ticket(), flow="complete"),
               dataclasses.replace(query_contacts(), flow="complete"),
               dataclasses.replace(query_assurances(), flow="complete"),
               dataclasses.replace(query_food(), flow="complete"),
               dataclasses.replace(preserve(), flow="complete")]
        self._create_order()
        out.append(dataclasses.replace(query_orders(), flow="complete"))
        o = self._first(paid=False)
        if o is not None:
            out.append(dataclasses.replace(pay_one_order(o.order_id), flow="complete"))
            o.paid = True
            out.append(dataclasses.replace(collect_one_order(o.order_id), flow="complete"))
            out.append(dataclasses.replace(enter_station(o.order_id), flow="complete"))
            self._orders.remove(o)      # ticket used; keep state bounded
        return out

    def iteration(self) -> List[RequestSpec]:
        """One full pass over all five flows (run_all_services_test:429)."""
        specs: List[RequestSpec] = []
        if self._iteration % 10 == 0:  # token refresh cadence :436-441
            specs.append(dataclasses.replace(login(), flow="token_refresh"))
        self._iteration += 1
        specs += self.core_business_flow()
        specs += self.auxiliary_flow()
        specs += self.admin_flow()
        specs += self.extended_flow()
        specs += self.complete_business_flow()
        return specs

    def run(self, iterations: int = 1) -> List[RequestSpec]:
        out: List[RequestSpec] = []
        for _ in range(iterations):
            out += self.iteration()
        return out


# ---------------------------------------------------------------------------
# Gateway: execute a request program against the synthetic SUT
# ---------------------------------------------------------------------------

# Baseline latency model: gateway + service handling, lognormal-ish.
_BASE_LATENCY_MS = 18.0


class SyntheticGateway:
    """Deterministic executor: routes each spec, applies active chaos
    effects, and accumulates ApiBatch records (the synthetic analog of the
    live cluster behind the NodePort gateway)."""

    def __init__(self, seed: int = 0, controller=None,
                 base_time_s: float = 1.7e9) -> None:
        self._rng = np.random.default_rng(seed)
        self._controller = controller
        self._t = base_time_s
        self._rows: List[Tuple[str, float, int, float, int]] = []

    def execute(self, specs: Sequence[RequestSpec]) -> List[int]:
        statuses = []
        for s in specs:
            svc = s.service
            lat_mult, err_p = (1.0, 0.002)
            if self._controller is not None:
                lat_mult, err_p = self._controller.active_effects(svc)
            lat = float(_BASE_LATENCY_MS *
                        np.exp(self._rng.normal(0.0, 0.35)) * lat_mult)
            fail = bool(self._rng.random() < err_p)
            status = 200
            if fail:
                status = 503 if err_p >= 0.5 else 500
            self._t += lat / 1e3 + float(self._rng.exponential(0.05))
            # content_length records the dominant byte flow of the exchange:
            # the synthesized request body for POSTs that carry one (so the
            # artifact histogram reflects the wrk2 content model), else the
            # synthetic response payload.
            if fail:
                nbytes = 0
            elif s.body is not None:
                nbytes = len(s.body)
            else:
                nbytes = int(self._rng.integers(64, 2048))
            self._rows.append((s.endpoint, self._t, status, lat, nbytes))
            statuses.append(status)
        return statuses

    @property
    def rows(self) -> List[Tuple[str, float, int, float, int]]:
        """Accumulated (endpoint, t_s, status, latency_ms, bytes) records."""
        return list(self._rows)

    @property
    def last_row(self) -> Tuple[str, float, int, float, int]:
        return self._rows[-1]

    def to_api_batch(self) -> ApiBatch:
        endpoints = tuple(sorted({r[0] for r in self._rows}))
        idx = {e: i for i, e in enumerate(endpoints)}
        return ApiBatch(
            endpoint=np.array([idx[r[0]] for r in self._rows], np.int32),
            t_s=np.array([r[1] for r in self._rows], np.float64),
            status=np.array([r[2] for r in self._rows], np.int16),
            latency_ms=np.array([r[3] for r in self._rows], np.float32),
            content_length=np.array([r[4] for r in self._rows], np.int32),
            endpoints=endpoints)


def run_scenario(iterations: int = 1, seed: int = 0,
                 controller=None) -> ApiBatch:
    """Drive the full scenario suite and return the collected ApiBatch."""
    driver = ScenarioDriver(seed=seed)
    gw = SyntheticGateway(seed=seed, controller=controller)
    gw.execute(driver.run(iterations))
    return gw.to_api_batch()


def services_covered(specs: Sequence[RequestSpec]) -> List[str]:
    return sorted({s.service for s in specs})
