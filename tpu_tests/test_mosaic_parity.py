"""Mosaic-compiled parity for every Pallas kernel (round-2 weak #2).

The CPU-mesh suite proves kernel *logic* via interpret mode; this module
proves the *compiled* kernels — Mosaic layouts, bf16 hi/lo numerics on the
real MXU, VMEM residency at the bench block size (4096), the revisited
output block across grid steps, and the shard_map ``check_vma=False``
composition — against the same numpy oracles, on a real synthetic corpus
at production shapes.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tt_corpus():
    """A real multi-experiment TT corpus staged exactly like bench.py
    (all 13 labels so the service vocabulary and sid range match the
    production replay), small enough to stage in seconds."""
    from anomod import labels, synth
    from anomod.replay import ReplayConfig, stage_columns
    from anomod.schemas import concat_span_batches

    batches = [synth.generate_spans(l, n_traces=60)
               for l in labels.labels_for_testbed("TT")]
    batch = concat_span_batches(batches)
    cfg = ReplayConfig(n_services=batch.n_services)
    chunks, n = stage_columns(batch, cfg)
    return batch, cfg, chunks, n


def test_replay_kernel_compiled_production_shape(tt_corpus):
    """Fused replay kernel, Mosaic-compiled at the bench configuration
    (block=4096, full TT service vocabulary) vs the numpy oracle."""
    from anomod.ops.pallas_replay import make_pallas_replay_fn
    from anomod.replay import pallas_block, replay_numpy, stage_pallas_planes

    _, cfg, chunks, _ = tt_corpus
    sid, planes = stage_pallas_planes(chunks)
    fn = make_pallas_replay_fn(cfg.sw, cfg.n_hist_buckets,
                               block=pallas_block(cfg.chunk_size))
    out = np.asarray(fn(sid, planes))
    ref = replay_numpy(chunks, cfg)
    # same tolerance contract as the interpret-mode test: 0/1 planes and
    # histogram exact, moments within the bf16 hi/lo split's error
    np.testing.assert_allclose(out[:, :3], ref.agg[:, :3], rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 6:], ref.hist, rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 3:6], ref.agg[:, 3:6], rtol=2e-3,
                               atol=1e-2)


def test_replay_kernel_compiled_inner_repeats(tt_corpus):
    """The bench measurement trick — replaying the staged corpus via the
    outer grid dimension — must accumulate exactly r copies of the state
    when compiled (revisited-output-block semantics under Mosaic)."""
    from anomod.ops.pallas_replay import make_pallas_replay_fn
    from anomod.replay import pallas_block, replay_numpy, stage_pallas_planes

    _, cfg, chunks, _ = tt_corpus
    sid, planes = stage_pallas_planes(chunks)
    r = 3
    fn = make_pallas_replay_fn(cfg.sw, cfg.n_hist_buckets,
                               block=pallas_block(cfg.chunk_size),
                               inner_repeats=r)
    out = np.asarray(fn(sid, planes))
    ref = replay_numpy(chunks, cfg)
    np.testing.assert_allclose(out[:, :3], r * ref.agg[:, :3], rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 6:], r * ref.hist, rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 3:6], r * ref.agg[:, 3:6], rtol=2e-3,
                               atol=3e-2)


def test_replay_sorted_kernel_compiled(tt_corpus):
    """Sorted-window kernel, Mosaic-compiled at production shape: the
    128-lane local one-hot, the scalar-prefetched window ids, and the
    dynamic-slice accumulate into the resident block — vs the numpy
    oracle, including inner_repeats accumulation."""
    from anomod.ops.pallas_replay import (make_pallas_replay_sorted_fn,
                                          stage_sorted_planes)
    from anomod.replay import pallas_block, replay_numpy, stage_pallas_planes

    _, cfg, chunks, _ = tt_corpus
    sid, planes = stage_pallas_planes(chunks)
    block = pallas_block(cfg.chunk_size)
    sid_l, planes_s, wids = stage_sorted_planes(sid, planes, cfg.sw,
                                                block=block)
    r = 2
    fn = make_pallas_replay_sorted_fn(cfg.sw, cfg.n_hist_buckets,
                                      block=block, inner_repeats=r)
    out = np.asarray(fn(sid_l, planes_s, wids))
    ref = replay_numpy(chunks, cfg)
    np.testing.assert_allclose(out[:, :3], r * ref.agg[:, :3], rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 6:], r * ref.hist, rtol=0, atol=0)
    np.testing.assert_allclose(out[:, 3:6], r * ref.agg[:, 3:6], rtol=2e-3,
                               atol=3e-2)


def test_lane_delta_kernel_compiled():
    """The serving plane's fused lane-stacked score kernel (ISSUE-7),
    Mosaic-compiled at serve shapes: [lanes, width] stacked chunks →
    per-lane deltas as ONE kernel launch, vs the per-lane numpy oracle.
    Dead pad lanes must come back exactly zero.  (The CPU-interpret twin
    runs in tier-1: tests/test_replay.py.)"""
    import jax

    from anomod.replay import (ReplayConfig, dead_chunk, make_lane_delta,
                               replay_numpy, stage_columns)
    from anomod import labels, synth

    cfg = ReplayConfig(n_services=12, n_windows=32,
                       window_us=5_000_000, chunk_size=4096)  # serve shape
    lanes = []
    for i, l in enumerate(labels.labels_for_testbed("TT")[:4]):
        b = synth.generate_spans(l, n_traces=40, seed=i)
        b = b._replace(service=b.service % cfg.n_services,
                       services=b.services[:cfg.n_services])
        staged, _ = stage_columns(b, cfg, t0_us=0)
        lanes.append({k: v[0] for k, v in staged.items()})
    lanes.append(dead_chunk(cfg, cfg.chunk_size, xp=np))
    stack = {k: np.stack([np.asarray(c[k]) for c in lanes])
             for k in lanes[0]}
    fn = jax.jit(make_lane_delta(cfg, engine="pallas"))
    dagg, dhist = fn(stack)
    dagg, dhist = np.asarray(dagg), np.asarray(dhist)
    for i, chunk in enumerate(lanes):
        ref = replay_numpy({k: np.asarray(v)[None] for k, v in
                            chunk.items()}, cfg)
        np.testing.assert_allclose(dagg[i, :, :3], ref.agg[:, :3],
                                   rtol=0, atol=0)
        np.testing.assert_allclose(dhist[i], ref.hist, rtol=0, atol=0)
        np.testing.assert_allclose(dagg[i, :, 3:6], ref.agg[:, 3:6],
                                   rtol=2e-3, atol=1e-2)
    assert (dagg[-1] == 0).all() and (dhist[-1] == 0).all()


def test_sharded_replay_pallas_compiled(tt_corpus):
    """make_sharded_replay_fn(kernel='pallas') on a real-device mesh: the
    compiled kernel inside shard_map with check_vma=False, psum merge."""
    import jax
    from jax.sharding import Mesh

    from anomod.parallel.replay import make_sharded_replay_fn, stage_sharded
    from anomod.replay import replay_numpy

    batch, cfg, chunks, _ = tt_corpus
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    dev_chunks, _ = stage_sharded(batch, mesh, cfg)
    fn = make_sharded_replay_fn(cfg, mesh, kernel="pallas")
    state = fn(dev_chunks)
    ref = replay_numpy(chunks, cfg)
    np.testing.assert_allclose(np.asarray(state.hist), ref.hist, rtol=0,
                               atol=0)
    np.testing.assert_allclose(np.asarray(state.agg)[:, :3], ref.agg[:, :3],
                               rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(state.agg)[:, 3:6], ref.agg[:, 3:6],
                               rtol=2e-3, atol=1e-2)


def test_tdigest_kernel_compiled():
    """t-digest build + merge through the Mosaic-compiled MXU reduction at
    production lane counts (a TT service plane's worth of digest lanes)."""
    from anomod.ops.pallas_tdigest import (tdigest_build_pallas,
                                           tdigest_merge_pallas)
    from anomod.ops.tdigest import tdigest_build, tdigest_merge

    rng = np.random.default_rng(3)
    a = rng.lognormal(3.0, 1.0, size=(96, 1024)).astype(np.float32)
    b = rng.lognormal(3.5, 0.8, size=(96, 1024)).astype(np.float32)
    ra = tdigest_build(a, k=64)
    pa = tdigest_build_pallas(a, k=64)
    np.testing.assert_allclose(np.asarray(pa.weight), ra.weight, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pa.mean), ra.mean, rtol=1e-3,
                               atol=1e-3)
    ref = tdigest_merge(ra, tdigest_build(b, k=64))
    out = tdigest_merge_pallas(pa, tdigest_build_pallas(b, k=64))
    np.testing.assert_allclose(np.asarray(out.weight), ref.weight, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.mean), ref.mean, rtol=1e-3,
                               atol=1e-3)


def test_hll_kernel_compiled():
    """HLL register kernel compiled: hashing, branchless clz, and the
    revisited max-accumulated output block must match the numpy oracle
    register-for-register."""
    from anomod.ops.hll import hll_add, hll_estimate, hll_init
    from anomod.ops.pallas_hll import make_pallas_hll_fn

    p = 10
    items = (np.arange(65536, dtype=np.int64) * 2654435761 % (2**31)
             ).astype(np.int32)
    ref = hll_add(hll_init(p), items, p=p)
    fn = make_pallas_hll_fn(p=p, block=2048)
    out = np.asarray(fn(items))
    np.testing.assert_array_equal(out, ref)
    est = hll_estimate(out)
    assert abs(est - 65536) / 65536 < 0.05
