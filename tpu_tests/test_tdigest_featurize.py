"""Compiled t-digest featurization path: parity + micro-bench vs the jax
build.  The measured trail (0.956x at the replay-plane shape, 0.971x at
long skewed lanes) demoted the Mosaic kernel to opt-in
(``ANOMOD_TDIGEST_ENGINE=pallas``); these tests keep the parity contract
and re-capture the rematch records on every watcher revival so a tree
that changes the verdict carries a committed record saying so.

Writes ``tdigest_featurize_micro`` / ``_large_lanes`` provenance records
with the median walls of both engines so the docs table can cite a
committed artifact.
"""

import time

import numpy as np


def _median_wall(fn, *args, repeats=5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        walls.append(time.perf_counter() - t0)
    return sorted(walls)[len(walls) // 2], walls


def test_replay_percentiles_engines_on_tpu():
    """engine='auto' resolves to the XLA build on a TPU backend (the Mosaic
    kernel measured 0.956x/0.971x vs XLA at both production regimes — see
    _resolve_tdigest_engine — so it is opt-in only); both the auto/XLA
    plane and the opt-in kernel plane must agree with the host digests."""
    import os

    from anomod import labels, synth
    from anomod.replay import (ReplayConfig, _resolve_tdigest_engine,
                               replay_percentiles)
    from anomod.schemas import concat_span_batches

    # the resolution assert tests the DEFAULT: an operator's opt-in
    # ANOMOD_TDIGEST_ENGINE export must not redefine what "auto" means here
    os.environ.pop("ANOMOD_TDIGEST_ENGINE", None)
    assert _resolve_tdigest_engine("auto") == "xla"
    batch = concat_span_batches([
        synth.generate_spans(l, n_traces=40)
        for l in labels.labels_for_testbed("TT")[:4]])
    cfg = ReplayConfig(n_services=batch.n_services, chunk_size=2048)
    auto = replay_percentiles(batch, cfg, qs=(0.5, 0.99))
    host = replay_percentiles(batch, cfg, qs=(0.5, 0.99), engine="host")
    np.testing.assert_allclose(auto, host, rtol=2e-3, atol=1e-2)
    pal = replay_percentiles(batch, cfg, qs=(0.5, 0.99), engine="pallas")
    np.testing.assert_allclose(pal, host, rtol=2e-3, atol=1e-2)
    nonzero = host[:, 0] > 0
    assert nonzero.any()
    assert (auto[nonzero, 1] >= auto[nonzero, 0]).all()


def _featurize_micro(n, S, lane_rng_seed, metric, floor):
    """Shared engine: build identical staged lanes, time the Mosaic kernel
    vs the XLA one-hot build, check parity, write a provenance record."""
    import jax
    import jax.numpy as jnp

    from anomod.ops.pallas_tdigest import make_pallas_tdigest_fn, _scale_pass
    from anomod.ops.tdigest import segment_pad, tdigest_build
    from anomod.provenance import capture_record, write_capture

    rng = np.random.default_rng(lane_rng_seed)
    seg = rng.integers(0, S, n).astype(np.int32)
    vals = np.log1p(rng.lognormal(10.0, 1.0, n)).astype(np.float32)
    padded, weights = segment_pad(vals, seg, S, pad_to=128)
    k = 64
    L = padded.shape[1]

    jax_build = jax.jit(lambda p, w: tdigest_build(p, k=k, weights=w, xp=jnp))

    kern = make_pallas_tdigest_fn(k, L)

    @jax.jit
    def pallas_build(p, w):
        bucket, ws, wv = _scale_pass(p, w, k)
        return kern(bucket, ws, wv)

    p_dev = jnp.asarray(padded)
    w_dev = jnp.asarray(weights)
    jax_wall, jax_raw = _median_wall(jax_build, p_dev, w_dev)
    pal_wall, pal_raw = _median_wall(pallas_build, p_dev, w_dev)

    # parity between the two engines on the same staged lanes
    ref = jax.device_get(jax_build(p_dev, w_dev))
    mean, weight = jax.device_get(pallas_build(p_dev, w_dev))
    np.testing.assert_allclose(weight, ref.weight, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(mean, ref.mean, rtol=2e-3, atol=1e-2)

    rec = capture_record(
        metric, round(n / pal_wall, 1), "values/sec",
        device=str(jax.devices()[0]), kernel="pallas", n_values=n,
        n_segments=S, lane_len=L, k=k,
        pallas_wall_s=round(pal_wall, 5),
        pallas_raw_wall_s=[round(t, 5) for t in pal_raw],
        xla_wall_s=round(jax_wall, 5),
        xla_raw_wall_s=[round(t, 5) for t in jax_raw],
        speedup_vs_xla=round(jax_wall / pal_wall, 3))
    write_capture(rec)
    assert pal_wall <= jax_wall * floor, (pal_wall, jax_wall)


def test_tdigest_featurize_microbench_kernel_vs_jax():
    """Production-sized digest plane (one TT replay plane: 93 services x
    32 windows, ~336 values/lane).  Rematch record: the committed result
    (0.956x) is why auto no longer selects the kernel; the floor only
    guards against the opt-in kernel regressing far below XLA (>20%
    slower), not a win claim."""
    _featurize_micro(n=1_000_000, S=2976, lane_rng_seed=5,
                     metric="tdigest_featurize_micro", floor=1.2)


def test_tdigest_featurize_large_lanes():
    """Skewed plane: few segments with long lanes (L_max ~8k), where the
    XLA build's [R, L, K] intermediate is largest relative to useful work
    — the regime the kernel was designed to win, where it still measured
    0.971x; the committed record carries the ratio either way."""
    _featurize_micro(n=2_000_000, S=256, lane_rng_seed=6,
                     metric="tdigest_featurize_large_lanes", floor=1.2)
