"""Mosaic-compiled Pallas kernel tests — require a real TPU.

``tests/`` pins an 8-device virtual CPU mesh and exercises the Pallas
kernels only in interpret mode; this suite runs them through the actual
Mosaic compiler on the attached chip at production shapes (layouts, VMEM
budgets at the bench block size, the shard_map ``check_vma=False``
interaction).  It lives outside ``tests/`` because that conftest's CPU pin
applies at import to the whole pytest session.

Collection is gated on an out-of-process backend probe with a hard
deadline (a dead axon tunnel makes any in-process ``jax.devices()`` call
hang forever); without a TPU every test is skipped, so
``python -m pytest tpu_tests/ -q`` is safe to run anywhere.

Each completed TPU session writes a ``bench_runs/`` provenance record
(device string, per-test outcomes, git SHA), so Mosaic-compiled parity is
evidenced by committed artifacts even when the reviewer has no live device.
"""

import pytest

from anomod.utils.platform import probe_device_platform

_PLATFORM, _DIAG = probe_device_platform()
_RESULTS = {}


def pytest_collection_modifyitems(config, items):
    if _PLATFORM != "tpu":
        skip = pytest.mark.skip(
            reason=f"requires a live TPU backend (probe: {_DIAG})")
        for item in items:
            item.add_marker(skip)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call":
        _RESULTS[item.name] = rep.outcome


def pytest_sessionfinish(session, exitstatus):
    if _PLATFORM != "tpu" or not _RESULTS:
        return
    import jax

    from anomod.provenance import capture_record, write_capture
    n_passed = sum(1 for v in _RESULTS.values() if v == "passed")
    rec = capture_record(
        "tpu_kernel_parity", float(n_passed), "tests_passed",
        device=str(jax.devices()[0]), n_tests=len(_RESULTS),
        outcomes=dict(sorted(_RESULTS.items())), exitstatus=int(exitstatus))
    write_capture(rec)
