// anomod native runtime: ingestion hot loops in C++.
//
// The reference's collectors shell out per artifact (docker logs, kubectl
// logs — collect_log.sh, log_collector.py) and post-process line-by-line in
// bash/python.  Here the per-line scanning (log level classification +
// timestamp extraction) and JSONL field extraction run natively, exposed via
// a C ABI consumed with ctypes (anomod/io/native.py).
//
// Build: make -C native   (g++ -O3 -shared -fPIC -pthread)

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cctype>
#include <ctime>
#include <thread>
#include <vector>

namespace {

// case-insensitive substring search (ASCII)
inline bool contains_ci(const char* hay, size_t n, const char* needle) {
    const size_t m = std::strlen(needle);
    if (m > n) return false;
    const char c0l = (char)std::tolower(needle[0]);
    for (size_t i = 0; i + m <= n; ++i) {
        if ((char)std::tolower(hay[i]) != c0l) continue;
        size_t j = 1;
        for (; j < m; ++j)
            if ((char)std::tolower(hay[i + j]) != (char)std::tolower(needle[j]))
                break;
        if (j == m) return true;
    }
    return false;
}

// parse "YYYY-MM-DD[T ]HH:MM:SS" anywhere in the first 64 bytes -> epoch secs
inline double parse_ts(const char* line, size_t n) {
    const size_t limit = n < 64 ? n : 64;
    for (size_t i = 0; i + 19 <= limit; ++i) {
        const char* p = line + i;
        if (std::isdigit(p[0]) && std::isdigit(p[1]) && std::isdigit(p[2]) &&
            std::isdigit(p[3]) && p[4] == '-' && std::isdigit(p[5]) &&
            std::isdigit(p[6]) && p[7] == '-' && std::isdigit(p[8]) &&
            std::isdigit(p[9]) && (p[10] == ' ' || p[10] == 'T') &&
            std::isdigit(p[11]) && std::isdigit(p[12]) && p[13] == ':' &&
            std::isdigit(p[14]) && std::isdigit(p[15]) && p[16] == ':' &&
            std::isdigit(p[17]) && std::isdigit(p[18])) {
            std::tm tm{};
            tm.tm_year = (p[0]-'0')*1000 + (p[1]-'0')*100 + (p[2]-'0')*10 + (p[3]-'0') - 1900;
            tm.tm_mon  = (p[5]-'0')*10 + (p[6]-'0') - 1;
            tm.tm_mday = (p[8]-'0')*10 + (p[9]-'0');
            tm.tm_hour = (p[11]-'0')*10 + (p[12]-'0');
            tm.tm_min  = (p[14]-'0')*10 + (p[15]-'0');
            tm.tm_sec  = (p[17]-'0')*10 + (p[18]-'0');
            return (double)timegm(&tm);
        }
    }
    return 0.0;
}

}  // namespace

extern "C" {

// Classify lines: level 0=info 1=warn 2=error 3=other (matches
// anomod.schemas LOG_* codes; semantics of collect_log.sh:104-106 grep -c -i).
// Returns the number of lines written (<= max_lines).
int64_t anomod_scan_log(const char* text, int64_t len,
                        int8_t* levels_out, double* ts_out,
                        int64_t max_lines) {
    int64_t count = 0;
    const char* p = text;
    const char* end = text + len;
    while (p < end && count < max_lines) {
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        const size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
        int8_t lvl = 3;
        if (contains_ci(p, n, "error") || contains_ci(p, n, "exception")) lvl = 2;
        else if (contains_ci(p, n, "warn")) lvl = 1;
        else if (contains_ci(p, n, "info")) lvl = 0;
        levels_out[count] = lvl;
        ts_out[count] = parse_ts(p, n);
        ++count;
        if (!nl) break;
        p = nl + 1;
    }
    return count;
}

// Multithreaded variant over pre-split chunks of one large buffer.
int64_t anomod_scan_log_mt(const char* text, int64_t len,
                           int8_t* levels_out, double* ts_out,
                           int64_t max_lines, int32_t n_threads) {
    if (n_threads <= 1 || len < (1 << 20))
        return anomod_scan_log(text, len, levels_out, ts_out, max_lines);
    // split at line boundaries
    std::vector<int64_t> starts{0};
    for (int t = 1; t < n_threads; ++t) {
        int64_t pos = len * t / n_threads;
        const char* nl = (const char*)memchr(text + pos, '\n', (size_t)(len - pos));
        starts.push_back(nl ? (int64_t)(nl - text) + 1 : len);
    }
    starts.push_back(len);
    // count lines per chunk first (cheap memchr pass) to place outputs
    std::vector<int64_t> line_ofs(n_threads + 1, 0);
    for (int t = 0; t < n_threads; ++t) {
        int64_t c = 0;
        const char* p = text + starts[t];
        const char* endp = text + starts[t + 1];
        while (p < endp) {
            const char* nl = (const char*)memchr(p, '\n', (size_t)(endp - p));
            ++c;
            if (!nl) break;
            p = nl + 1;
        }
        line_ofs[t + 1] = line_ofs[t] + c;
    }
    const int64_t total = line_ofs[n_threads] < max_lines ? line_ofs[n_threads]
                                                          : max_lines;
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; ++t) {
        threads.emplace_back([=]() {
            const int64_t cap = total - (line_ofs[t] < total ? line_ofs[t] : total);
            if (cap <= 0) return;
            anomod_scan_log(text + starts[t], starts[t + 1] - starts[t],
                            levels_out + line_ofs[t], ts_out + line_ofs[t], cap);
        });
    }
    for (auto& th : threads) th.join();
    return total;
}

// Extract numeric fields from API-response JSONL (one object per line):
// status_code, latency_ms, content_length (enhanced_openapi_monitor.py
// record contract).  Returns number of records.
int64_t anomod_scan_api_jsonl(const char* text, int64_t len,
                              int16_t* status_out, float* latency_out,
                              int32_t* clen_out, int64_t max_recs) {
    int64_t count = 0;
    const char* p = text;
    const char* end = text + len;
    auto find_num = [](const char* line, size_t n, const char* key,
                       double* out) -> bool {
        const size_t klen = std::strlen(key);
        for (size_t i = 0; i + klen + 1 < n; ++i) {
            if (line[i] == '"' && i + 1 + klen < n &&
                std::memcmp(line + i + 1, key, klen) == 0 &&
                line[i + 1 + klen] == '"') {
                const char* q = line + i + 2 + klen;
                while (q < line + n && (*q == ':' || *q == ' ')) ++q;
                char* endq = nullptr;
                const double v = std::strtod(q, &endq);
                if (endq != q) { *out = v; return true; }
                return false;
            }
        }
        return false;
    };
    while (p < end && count < max_recs) {
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        const size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
        if (n > 2) {
            double st = 0, lat = 0, cl = 0;
            find_num(p, n, "status_code", &st);
            find_num(p, n, "latency_ms", &lat);
            find_num(p, n, "content_length", &cl);
            status_out[count] = (int16_t)st;
            latency_out[count] = (float)lat;
            clen_out[count] = (int32_t)cl;
            ++count;
        }
        if (!nl) break;
        p = nl + 1;
    }
    return count;
}

}  // extern "C"
