// anomod native runtime: ingestion hot loops + executor in C++.
//
// The reference's collectors shell out per artifact (docker logs, kubectl
// logs — collect_log.sh, log_collector.py) and post-process line-by-line in
// bash/python.  Here the per-line scanning (log level classification +
// timestamp extraction), JSONL/CSV field extraction, and the multi-file
// collection fan-out (the reference's per-service loop,
// collect_log.sh:84-110) run natively: a persistent thread-pool executor
// with reusable per-thread read buffers, exposed via a C ABI consumed with
// ctypes (anomod/io/native.py).
//
// Build: make -C native   (g++ -O3 -shared -fPIC -pthread)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <cctype>
#include <cmath>
#include <ctime>
#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace {

// case-insensitive substring search (ASCII)
inline bool contains_ci(const char* hay, size_t n, const char* needle) {
    const size_t m = std::strlen(needle);
    if (m > n) return false;
    const char c0l = (char)std::tolower(needle[0]);
    for (size_t i = 0; i + m <= n; ++i) {
        if ((char)std::tolower(hay[i]) != c0l) continue;
        size_t j = 1;
        for (; j < m; ++j)
            if ((char)std::tolower(hay[i + j]) != (char)std::tolower(needle[j]))
                break;
        if (j == m) return true;
    }
    return false;
}

// parse "YYYY-MM-DD[T ]HH:MM:SS" anywhere in the first 64 bytes -> epoch secs
inline double parse_ts(const char* line, size_t n) {
    const size_t limit = n < 64 ? n : 64;
    for (size_t i = 0; i + 19 <= limit; ++i) {
        const char* p = line + i;
        if (std::isdigit(p[0]) && std::isdigit(p[1]) && std::isdigit(p[2]) &&
            std::isdigit(p[3]) && p[4] == '-' && std::isdigit(p[5]) &&
            std::isdigit(p[6]) && p[7] == '-' && std::isdigit(p[8]) &&
            std::isdigit(p[9]) && (p[10] == ' ' || p[10] == 'T') &&
            std::isdigit(p[11]) && std::isdigit(p[12]) && p[13] == ':' &&
            std::isdigit(p[14]) && std::isdigit(p[15]) && p[16] == ':' &&
            std::isdigit(p[17]) && std::isdigit(p[18])) {
            std::tm tm{};
            tm.tm_year = (p[0]-'0')*1000 + (p[1]-'0')*100 + (p[2]-'0')*10 + (p[3]-'0') - 1900;
            tm.tm_mon  = (p[5]-'0')*10 + (p[6]-'0') - 1;
            tm.tm_mday = (p[8]-'0')*10 + (p[9]-'0');
            tm.tm_hour = (p[11]-'0')*10 + (p[12]-'0');
            tm.tm_min  = (p[14]-'0')*10 + (p[15]-'0');
            tm.tm_sec  = (p[17]-'0')*10 + (p[18]-'0');
            return (double)timegm(&tm);
        }
    }
    return 0.0;
}

// ---------------------------------------------------------------------------
// Thread-pool executor: fixed worker set, FIFO task queue, wait-all barrier.
// One pool outlives many batch submissions (the scheduler the bash reference
// approximates with `&`/`wait` subshells, collect_all_data.sh:319-346).
class Runtime {
 public:
    explicit Runtime(int n_threads) : stop_(false), active_(0) {
        if (n_threads < 1) n_threads = 1;
        for (int i = 0; i < n_threads; ++i)
            workers_.emplace_back([this] { worker_loop(); });
    }

    ~Runtime() {
        {
            std::unique_lock<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
    }

    void submit(std::function<void()> fn) {
        {
            std::unique_lock<std::mutex> lk(mu_);
            queue_.push(std::move(fn));
        }
        cv_.notify_one();
    }

    void wait_all() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return queue_.empty() && active_ == 0; });
    }

    int n_threads() const { return (int)workers_.size(); }

 private:
    void worker_loop() {
        for (;;) {
            std::function<void()> task;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
                if (stop_ && queue_.empty()) return;
                task = std::move(queue_.front());
                queue_.pop();
                ++active_;
            }
            task();
            {
                std::unique_lock<std::mutex> lk(mu_);
                --active_;
                if (queue_.empty() && active_ == 0) done_cv_.notify_all();
            }
        }
    }

    std::vector<std::thread> workers_;
    std::queue<std::function<void()>> queue_;
    std::mutex mu_;
    std::condition_variable cv_;
    std::condition_variable done_cv_;
    bool stop_;
    int active_;
};

// Per-thread growable read buffer, reused across files so a summarization
// sweep over a 13-experiment tree does one allocation per worker, not one
// per file.
thread_local std::vector<char> tl_read_buf;

// Read a whole file into the thread-local buffer; returns size or -1.
inline int64_t read_file(const char* path) {
    std::FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    std::fseek(f, 0, SEEK_END);
    const long sz = std::ftell(f);
    if (sz < 0) { std::fclose(f); return -1; }
    std::fseek(f, 0, SEEK_SET);
    if (tl_read_buf.size() < (size_t)sz) tl_read_buf.resize((size_t)sz);
    const size_t got = sz ? std::fread(tl_read_buf.data(), 1, (size_t)sz, f)
                          : 0;
    std::fclose(f);
    return (int64_t)got;
}

}  // namespace

extern "C" {

// ---- executor ABI ---------------------------------------------------------

void* anomod_rt_create(int32_t n_threads) {
    return new Runtime(n_threads);
}

void anomod_rt_destroy(void* rt) {
    delete static_cast<Runtime*>(rt);
}

int32_t anomod_rt_n_threads(void* rt) {
    return static_cast<Runtime*>(rt)->n_threads();
}

// Summarize N log files in parallel (the whole-experiment sweep of
// collect_log.sh:101-137 as one call): for each file emit
// counts_out[i*5..] = {n_lines, n_info, n_warn, n_error, size_bytes} and
// ts_out[i*2..] = {min_ts, max_ts} (0 when no timestamp parsed).
// Unreadable files get all-zero rows.  Returns the number of readable files.
int64_t anomod_rt_summarize_logs(void* rt_ptr, const char* const* paths,
                                 int64_t n_files, int64_t* counts_out,
                                 double* ts_out) {
    Runtime* rt = static_cast<Runtime*>(rt_ptr);
    std::vector<int64_t> ok(n_files, 0);
    for (int64_t i = 0; i < n_files; ++i) {
        rt->submit([i, paths, counts_out, ts_out, &ok] {
            int64_t* c = counts_out + i * 5;
            double* ts = ts_out + i * 2;
            c[0] = c[1] = c[2] = c[3] = c[4] = 0;
            ts[0] = ts[1] = 0.0;
            const int64_t sz = read_file(paths[i]);
            if (sz < 0) return;
            ok[i] = 1;
            c[4] = sz;
            const char* p = tl_read_buf.data();
            const char* end = p + sz;
            double tmin = 0.0, tmax = 0.0;
            while (p < end) {
                const char* nl =
                    (const char*)memchr(p, '\n', (size_t)(end - p));
                const size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
                ++c[0];
                if (contains_ci(p, n, "error") ||
                    contains_ci(p, n, "exception")) ++c[3];
                else if (contains_ci(p, n, "warn")) ++c[2];
                else if (contains_ci(p, n, "info")) ++c[1];
                const double t = parse_ts(p, n);
                if (t > 0.0) {
                    if (tmin == 0.0 || t < tmin) tmin = t;
                    if (t > tmax) tmax = t;
                }
                if (!nl) break;
                p = nl + 1;
            }
            ts[0] = tmin;
            ts[1] = tmax;
        });
    }
    rt->wait_all();
    int64_t readable = 0;
    for (int64_t i = 0; i < n_files; ++i) readable += ok[i];
    return readable;
}

// Extract numeric columns from a CSV buffer: for each row, parse the
// requested column indices with strtod (non-numeric/missing -> NaN).
// Accepted dialect: double-quoted fields may contain commas but NOT
// newlines; RFC-4180 escaped quotes ("") inside a field parse as NaN
// (non-numeric).  Callers needing full RFC-4180 must validate row counts
// against a real CSV parser and fall back (anomod/io/metrics.py does).
// Output is column-major: out[c * max_rows + r].  Returns rows parsed.
int64_t anomod_scan_csv_cols(const char* text, int64_t len,
                             const int32_t* cols, int32_t n_cols,
                             int32_t skip_header, double* out,
                             int64_t max_rows) {
    const double nan = std::nan("");
    int32_t max_col = 0;
    for (int32_t c = 0; c < n_cols; ++c)
        if (cols[c] > max_col) max_col = cols[c];
    std::vector<const char*> field_beg((size_t)max_col + 2);
    std::vector<size_t> field_len((size_t)max_col + 2);
    std::string scratch;  // reused NUL-terminated field copy for strtod
    int64_t row = 0;
    const char* p = text;
    const char* end = text + len;
    bool first = true;
    while (p < end && row < max_rows) {
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        const char* eol = nl ? nl : end;
        if (first && skip_header) {
            first = false;
            p = eol + 1;
            continue;
        }
        first = false;
        if (eol > p) {
            // split into fields up to max_col (quote-aware)
            int32_t nf = 0;
            const char* q = p;
            while (q <= eol && nf <= max_col) {
                const char* fb = q;
                size_t fl = 0;
                if (q < eol && *q == '"') {
                    // quoted field: skip over RFC-4180 escaped quotes ("")
                    // so the field span keeps them — the numeric parse below
                    // then sees the interior '"' and yields NaN, matching
                    // the pure-Python fallback (float('1.5"x') raises)
                    fb = ++q;
                    while (q < eol) {
                        if (*q == '"') {
                            if (q + 1 < eol && q[1] == '"') { q += 2; continue; }
                            break;
                        }
                        ++q;
                    }
                    fl = (size_t)(q - fb);
                    while (q < eol && *q != ',') ++q;
                } else {
                    while (q < eol && *q != ',') ++q;
                    fl = (size_t)(q - fb);
                }
                field_beg[nf] = fb;
                field_len[nf] = fl;
                ++nf;
                if (q >= eol) break;
                ++q;  // skip comma
            }
            for (int32_t c = 0; c < n_cols; ++c) {
                double v = nan;
                const size_t fl = cols[c] < nf ? field_len[cols[c]] : 0;
                // bound strtod by the field via a NUL-terminated copy into a
                // reused buffer (the raw buffer only stops it on ',' or '"'
                // by luck of the delimiters); an interior '"' means an
                // RFC-4180 escaped quote -> non-numeric
                if (fl > 0) {
                    const char* fb = field_beg[cols[c]];
                    if (memchr(fb, '"', fl) == nullptr) {
                        scratch.assign(fb, fl);
                        char* endq = nullptr;
                        const double parsed =
                            std::strtod(scratch.c_str(), &endq);
                        if (endq > scratch.c_str()) v = parsed;
                    }
                }
                out[(int64_t)c * max_rows + row] = v;
            }
            ++row;
        }
        if (!nl) break;
        p = nl + 1;
    }
    return row;
}

// Classify lines: level 0=info 1=warn 2=error 3=other (matches
// anomod.schemas LOG_* codes; semantics of collect_log.sh:104-106 grep -c -i).
// Returns the number of lines written (<= max_lines).
int64_t anomod_scan_log(const char* text, int64_t len,
                        int8_t* levels_out, double* ts_out,
                        int64_t max_lines) {
    int64_t count = 0;
    const char* p = text;
    const char* end = text + len;
    while (p < end && count < max_lines) {
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        const size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
        int8_t lvl = 3;
        if (contains_ci(p, n, "error") || contains_ci(p, n, "exception")) lvl = 2;
        else if (contains_ci(p, n, "warn")) lvl = 1;
        else if (contains_ci(p, n, "info")) lvl = 0;
        levels_out[count] = lvl;
        ts_out[count] = parse_ts(p, n);
        ++count;
        if (!nl) break;
        p = nl + 1;
    }
    return count;
}

// ---- serving-plane lane staging -------------------------------------------
//
// Pack one fused dispatch's lane-stacked scratch: for each 4-byte column
// buffer dst[c] (row-major [lanes, width]), copy each live lane's rows from
// its source slice and fill the row tail — plus every dead lane — with the
// column's 4-byte fill pattern (the dead-chunk fill: sid = SW, everything
// else 0).  This is the serve hot loop's host-side packing, moved off the
// Python interpreter: the ctypes call releases the GIL, so staging slot k+1
// overlaps the in-flight XLA dispatch on slot k and shard workers stage
// concurrently instead of convoying on the interpreter lock.
//
// Every chunk column is 4 bytes wide (int32 sid/tid, float32 the rest), so
// the copy is dtype-blind: memcpy the live rows, store the fill pattern in
// the tail.  Byte-identity with the Python fill (buf[i, :m] = c;
// buf[i, m:] = fill) is therefore structural.

namespace {

// Per-call completion latch: a staging call waits only for ITS OWN column
// tasks.  The pool's wait_all() is a global quiesce — two shard workers
// staging concurrently (or a stage racing an ingest scan on the shared
// default runtime) would convoy on each other's queues through it, which
// is exactly the serialization the GIL-free path exists to remove.
class Latch {
 public:
    explicit Latch(int n) : remaining_(n) {}
    void count_down() {
        std::unique_lock<std::mutex> lk(mu_);
        if (--remaining_ == 0) cv_.notify_all();
    }
    void wait() {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return remaining_ == 0; });
    }

 private:
    std::mutex mu_;
    std::condition_variable cv_;
    int remaining_;
};

inline void stage_one_column(uint32_t* d, const void* const* src_col,
                             const int64_t* n_rows, uint32_t fill,
                             int64_t n_live, int64_t lanes, int64_t width) {
    for (int64_t i = 0; i < n_live; ++i) {
        const int64_t m = n_rows[i];
        uint32_t* row = d + i * width;
        if (m > 0) std::memcpy(row, src_col[i], (size_t)m * 4);
        for (int64_t j = m; j < width; ++j) row[j] = fill;
    }
    for (int64_t i = n_live; i < lanes; ++i) {
        uint32_t* row = d + i * width;
        for (int64_t j = 0; j < width; ++j) row[j] = fill;
    }
}

}  // namespace

// Stage n_cols column buffers for one fused dispatch.  ``src`` is
// column-major: src[c * n_live + i] is live lane i's slice of column c,
// n_rows[i] elements long (identical across columns of a lane).  ``rt_ptr``
// may be a Runtime* to fan the per-column fills across the pool (worth it
// only for big slots; small ones stay on the calling thread), or NULL.
// Returns the number of 4-byte words staged, or -1 on malformed arguments —
// the Python caller treats -1 as "fall back to the interpreter fill".
int64_t anomod_stage_lanes(void* rt_ptr, void* const* dst,
                           const void* const* src, const int64_t* n_rows,
                           const uint32_t* fills, int32_t n_cols,
                           int32_t n_live, int64_t lanes, int64_t width) {
    if (n_cols < 1 || n_live < 0 || n_live > lanes || width < 1 ||
        lanes < 1)
        return -1;
    for (int32_t i = 0; i < n_live; ++i)
        if (n_rows[i] < 0 || n_rows[i] > width) return -1;
    Runtime* rt = static_cast<Runtime*>(rt_ptr);
    // pool fan-out threshold: below ~64K words per column the submit/wake
    // latency costs more than the copy
    if (rt != nullptr && n_cols > 1 && lanes * width >= (int64_t)1 << 16) {
        Latch latch(n_cols);
        for (int32_t c = 0; c < n_cols; ++c) {
            uint32_t* d = static_cast<uint32_t*>(dst[c]);
            const void* const* src_col = src + (int64_t)c * n_live;
            const uint32_t fill = fills[c];
            rt->submit([d, src_col, n_rows, fill, n_live, lanes, width,
                        &latch] {
                stage_one_column(d, src_col, n_rows, fill, n_live, lanes,
                                 width);
                latch.count_down();
            });
        }
        latch.wait();
    } else {
        for (int32_t c = 0; c < n_cols; ++c)
            stage_one_column(static_cast<uint32_t*>(dst[c]),
                             src + (int64_t)c * n_live, n_rows, fills[c],
                             n_live, lanes, width);
    }
    return (int64_t)n_cols * lanes * width;
}

// Matrix-carrier twin of anomod_stage_lanes: each live lane's columns are
// rows of ONE C-contiguous 4-byte matrix (anomod.replay.stage_columns_fused
// stages them that way), so a lane is described by a single base pointer +
// row stride instead of n_cols separate pointers — the Python caller's
// pointer extraction (the expensive part of ctypes marshalling) drops from
// n_cols*n_live to one per STAGED BATCH, amortized across its chunks.
// Column c of lane i starts at (uint32_t*)bases[i] + c * strides[i]
// (strides in 4-byte elements).  Fill/parity semantics identical to
// anomod_stage_lanes; returns words staged or -1 on malformed arguments.
int64_t anomod_stage_lanes_mat(void* rt_ptr, void* const* dst,
                               const void* const* bases,
                               const int64_t* strides,
                               const int64_t* n_rows, const uint32_t* fills,
                               int32_t n_cols, int32_t n_live,
                               int64_t lanes, int64_t width) {
    if (n_cols < 1 || n_live < 0 || n_live > lanes || width < 1 ||
        lanes < 1)
        return -1;
    for (int32_t i = 0; i < n_live; ++i)
        if (n_rows[i] < 0 || n_rows[i] > width || strides[i] < n_rows[i])
            return -1;
    Runtime* rt = static_cast<Runtime*>(rt_ptr);
    auto stage_col = [=](int32_t c) {
        uint32_t* d = static_cast<uint32_t*>(dst[c]);
        const uint32_t fill = fills[c];
        for (int64_t i = 0; i < n_live; ++i) {
            const int64_t m = n_rows[i];
            uint32_t* row = d + i * width;
            if (m > 0)
                std::memcpy(row,
                            static_cast<const uint32_t*>(bases[i]) +
                                c * strides[i],
                            (size_t)m * 4);
            for (int64_t j = m; j < width; ++j) row[j] = fill;
        }
        for (int64_t i = n_live; i < lanes; ++i) {
            uint32_t* row = d + i * width;
            for (int64_t j = 0; j < width; ++j) row[j] = fill;
        }
    };
    // pool fan-out threshold: below ~64K words per column the submit/wake
    // latency costs more than the copy
    if (rt != nullptr && n_cols > 1 && lanes * width >= (int64_t)1 << 16) {
        Latch latch(n_cols);
        for (int32_t c = 0; c < n_cols; ++c)
            rt->submit([stage_col, c, &latch] {
                stage_col(c);
                latch.count_down();
            });
        latch.wait();
    } else {
        for (int32_t c = 0; c < n_cols; ++c) stage_col(c);
    }
    return (int64_t)n_cols * lanes * width;
}

// ---- admission-plane columnar SFQ kernels ---------------------------------
//
// The serve tick's admission drain/shed loop (anomod/serve/queues.py) keeps
// its pending-batch book as parallel columns: finish tag (double), admission
// seq (int64, unique), span count (int64), priority (int64) and an alive
// mask (uint8), all n slots long (dead slots are skipped, the lazy-deletion
// idiom of the Python heaps these kernels replace).  Both kernels are pure
// functions over caller-owned arrays — no shared or static state — so
// concurrent callers (the sanitize hammer drives them from N threads) race
// only if the caller shares arrays.  The GIL is released for the whole call.
//
// Byte-parity contract with the Python heap oracle:
// - drain: candidates sorted ascending by (fin, seq) == the drain heap's
//   pop order; the budget walk is the SAME sequential float64 subtraction
//   (select while remaining > 0, then remaining -= n_spans — the one-batch
//   overdraw included), so the selected set and its order are identical.
// - victim: lexicographic argmax of (pri, fin, seq) over alive slots ==
//   the lazy evict heap's top (ordered by (-pri, -fin, -seq)).

// Select the slots a drain of ``budget`` spans serves, in SFQ order.
// Writes selected slot indices to out_idx; returns the count, or -1 on
// malformed arguments — the Python caller treats -1 as "fall back to the
// NumPy scan".
int64_t anomod_sfq_drain(const double* fin, const int64_t* seq,
                         const int64_t* nsp, const uint8_t* alive,
                         int64_t n, double budget, int64_t* out_idx) {
    if (!fin || !seq || !nsp || !alive || !out_idx || n < 0) return -1;
    std::vector<int64_t> cand;
    cand.reserve((size_t)n);
    for (int64_t i = 0; i < n; ++i)
        if (alive[i]) cand.push_back(i);
    std::sort(cand.begin(), cand.end(), [&](int64_t a, int64_t b) {
        if (fin[a] != fin[b]) return fin[a] < fin[b];
        return seq[a] < seq[b];
    });
    double remaining = budget;
    int64_t count = 0;
    for (int64_t i : cand) {
        if (!(remaining > 0.0)) break;
        remaining -= (double)nsp[i];
        out_idx[count++] = i;
    }
    return count;
}

// The eviction candidate's slot: lexicographic max of (pri, fin, seq) over
// the alive slots.  Returns -1 when no slot is alive (or on malformed
// arguments); the Python caller applies the strictly-lower-priority check.
int64_t anomod_sfq_victim(const double* fin, const int64_t* seq,
                          const int64_t* pri, const uint8_t* alive,
                          int64_t n) {
    if (!fin || !seq || !pri || !alive || n < 0) return -1;
    int64_t best = -1;
    for (int64_t i = 0; i < n; ++i) {
        if (!alive[i]) continue;
        if (best < 0 || pri[i] > pri[best] ||
            (pri[i] == pri[best] &&
             (fin[i] > fin[best] ||
              (fin[i] == fin[best] && seq[i] > seq[best]))))
            best = i;
    }
    return best;
}

// Multithreaded variant over pre-split chunks of one large buffer.
int64_t anomod_scan_log_mt(const char* text, int64_t len,
                           int8_t* levels_out, double* ts_out,
                           int64_t max_lines, int32_t n_threads) {
    if (n_threads <= 1 || len < (1 << 20))
        return anomod_scan_log(text, len, levels_out, ts_out, max_lines);
    // split at line boundaries
    std::vector<int64_t> starts{0};
    for (int t = 1; t < n_threads; ++t) {
        int64_t pos = len * t / n_threads;
        const char* nl = (const char*)memchr(text + pos, '\n', (size_t)(len - pos));
        starts.push_back(nl ? (int64_t)(nl - text) + 1 : len);
    }
    starts.push_back(len);
    // count lines per chunk first (cheap memchr pass) to place outputs
    std::vector<int64_t> line_ofs(n_threads + 1, 0);
    for (int t = 0; t < n_threads; ++t) {
        int64_t c = 0;
        const char* p = text + starts[t];
        const char* endp = text + starts[t + 1];
        while (p < endp) {
            const char* nl = (const char*)memchr(p, '\n', (size_t)(endp - p));
            ++c;
            if (!nl) break;
            p = nl + 1;
        }
        line_ofs[t + 1] = line_ofs[t] + c;
    }
    const int64_t total = line_ofs[n_threads] < max_lines ? line_ofs[n_threads]
                                                          : max_lines;
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; ++t) {
        threads.emplace_back([=]() {
            const int64_t cap = total - (line_ofs[t] < total ? line_ofs[t] : total);
            if (cap <= 0) return;
            anomod_scan_log(text + starts[t], starts[t + 1] - starts[t],
                            levels_out + line_ofs[t], ts_out + line_ofs[t], cap);
        });
    }
    for (auto& th : threads) th.join();
    return total;
}

// Extract numeric fields from API-response JSONL (one object per line):
// status_code, latency_ms, content_length (enhanced_openapi_monitor.py
// record contract).  Returns number of records.
int64_t anomod_scan_api_jsonl(const char* text, int64_t len,
                              int16_t* status_out, float* latency_out,
                              int32_t* clen_out, int64_t max_recs) {
    int64_t count = 0;
    const char* p = text;
    const char* end = text + len;
    auto find_num = [](const char* line, size_t n, const char* key,
                       double* out) -> bool {
        const size_t klen = std::strlen(key);
        for (size_t i = 0; i + klen + 1 < n; ++i) {
            if (line[i] == '"' && i + 1 + klen < n &&
                std::memcmp(line + i + 1, key, klen) == 0 &&
                line[i + 1 + klen] == '"') {
                const char* q = line + i + 2 + klen;
                while (q < line + n && (*q == ':' || *q == ' ')) ++q;
                char* endq = nullptr;
                const double v = std::strtod(q, &endq);
                if (endq != q) { *out = v; return true; }
                return false;
            }
        }
        return false;
    };
    while (p < end && count < max_recs) {
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        const size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
        if (n > 2) {
            double st = 0, lat = 0, cl = 0;
            find_num(p, n, "status_code", &st);
            find_num(p, n, "latency_ms", &lat);
            find_num(p, n, "content_length", &cl);
            status_out[count] = (int16_t)st;
            latency_out[count] = (float)lat;
            clen_out[count] = (int32_t)cl;
            ++count;
        }
        if (!nl) break;
        p = nl + 1;
    }
    return count;
}

}  // extern "C"
