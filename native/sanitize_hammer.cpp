// Sanitizer hammer for the GIL-free staging path (PR 7).
//
// The hardest-to-review code in the repo is anomod_stage_lanes /
// anomod_stage_lanes_mat: the GIL is released, pointer/stride fills land in
// pinned scratch, and multiple shard workers stage CONCURRENTLY through one
// shared Runtime pool (its task queue, completion Latch and thread-local
// read buffers are the race surface).  This driver reproduces the Python
// StagePlan fill pattern — each worker owns `depth` pinned scratch slots
// (the pipeline-slot discipline: a slot refills only after its dispatch
// materialized) while ALL workers share the Runtime — as a standalone
// binary so `make tsan` / `make asan` can compile the whole native layer
// with -fsanitize=thread/address and run it.  (A TSan-instrumented .so
// cannot be dlopen'd into an uninstrumented CPython, so the hammer drives
// the same extern "C" entry points natively; the byte-parity oracle below
// is the same fill contract tests/test_native.py pins from Python.)
//
// Exit codes: 0 = clean, 2 = byte-parity mismatch (the fill produced wrong
// bytes), anything else = sanitizer abort (TSAN_OPTIONS/ASAN_OPTIONS
// exitcode).
//
// Build + run: make -C native tsan   (or: make -C native asan)

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <atomic>
#include <thread>
#include <vector>

extern "C" {
void* anomod_rt_create(int32_t n_threads);
void anomod_rt_destroy(void* rt);
int64_t anomod_stage_lanes(void* rt_ptr, void* const* dst,
                           const void* const* src, const int64_t* n_rows,
                           const uint32_t* fills, int32_t n_cols,
                           int32_t n_live, int64_t lanes, int64_t width);
int64_t anomod_stage_lanes_mat(void* rt_ptr, void* const* dst,
                               const void* const* bases,
                               const int64_t* strides, const int64_t* n_rows,
                               const uint32_t* fills, int32_t n_cols,
                               int32_t n_live, int64_t lanes, int64_t width);
int64_t anomod_sfq_drain(const double* fin, const int64_t* seq,
                         const int64_t* nsp, const uint8_t* alive,
                         int64_t n, double budget, int64_t* out_idx);
int64_t anomod_sfq_victim(const double* fin, const int64_t* seq,
                          const int64_t* pri, const uint8_t* alive,
                          int64_t n);
}

namespace {

constexpr int kCols = 7;        // STAGE_KEYS: the serve plane's 7 columns

// deterministic per-thread source data (no global RNG: the hammer itself
// honors the determinism contract it guards)
inline uint32_t lcg(uint32_t& s) { return s = s * 1664525u + 1013904223u; }

struct Slot {
    // one pinned scratch slot: kCols column buffers of [lanes, width]
    std::vector<std::vector<uint32_t>> cols;
    explicit Slot(int64_t lanes, int64_t width)
        : cols(kCols, std::vector<uint32_t>((size_t)(lanes * width),
                                            0xdeadbeefu)) {}
};

// the fill contract (tests/test_native.py's Python oracle, restated):
// live rows byte-copied, row tails + dead lanes = the column fill
bool verify(const Slot& slot, const std::vector<std::vector<uint32_t>>& src,
            const std::vector<int64_t>& n_rows, const uint32_t* fills,
            int32_t n_live, int64_t lanes, int64_t width) {
    for (int c = 0; c < kCols; ++c) {
        const uint32_t* d = slot.cols[c].data();
        for (int64_t i = 0; i < lanes; ++i) {
            const int64_t m = i < n_live ? n_rows[i] : 0;
            const uint32_t* row = d + i * width;
            if (m > 0 && std::memcmp(row, src[(size_t)(c * n_live + i)]
                                              .data(),
                                     (size_t)m * 4) != 0)
                return false;
            for (int64_t j = m; j < width; ++j)
                if (row[j] != fills[c]) return false;
        }
    }
    return true;
}

std::atomic<int> failures{0};

void worker(void* rt, int tid, int iters, int depth, int32_t n_live,
            int64_t lanes, int64_t width) {
    uint32_t seed = 0x9e3779b9u * (uint32_t)(tid + 1);
    std::vector<Slot> slots;
    for (int d = 0; d < depth; ++d) slots.emplace_back(lanes, width);
    // column-major source slices: src[c * n_live + i] = lane i, column c
    std::vector<std::vector<uint32_t>> src((size_t)(kCols * n_live));
    std::vector<int64_t> n_rows((size_t)n_live);
    uint32_t fills[kCols];
    for (int it = 0; it < iters; ++it) {
        for (int c = 0; c < kCols; ++c) fills[c] = lcg(seed);
        for (int32_t i = 0; i < n_live; ++i) {
            n_rows[(size_t)i] = (int64_t)(lcg(seed) % (uint32_t)(width + 1));
            for (int c = 0; c < kCols; ++c) {
                auto& s = src[(size_t)(c * n_live + i)];
                s.resize((size_t)n_rows[(size_t)i]);
                for (auto& v : s) v = lcg(seed);
            }
        }
        Slot& slot = slots[(size_t)(it % depth)];
        std::vector<void*> dst(kCols);
        for (int c = 0; c < kCols; ++c) dst[c] = slot.cols[c].data();
        std::vector<const void*> sp((size_t)(kCols * n_live));
        for (size_t k = 0; k < sp.size(); ++k) sp[k] = src[k].data();
        const int64_t got = anomod_stage_lanes(
            rt, dst.data(), sp.data(), n_rows.data(), fills, kCols,
            n_live, lanes, width);
        if (got != (int64_t)kCols * lanes * width ||
            !verify(slot, src, n_rows, fills, n_live, lanes, width))
            ++failures;
        // matrix-carrier twin: lane i's columns as rows of ONE matrix
        // (the stage_columns_fused layout), strides = width of the lane
        std::vector<std::vector<uint32_t>> mats((size_t)n_live);
        std::vector<const void*> bases((size_t)n_live);
        std::vector<int64_t> strides((size_t)n_live);
        for (int32_t i = 0; i < n_live; ++i) {
            const int64_t m = n_rows[(size_t)i];
            auto& mat = mats[(size_t)i];
            mat.resize((size_t)(kCols * (m > 0 ? m : 1)));
            strides[(size_t)i] = m > 0 ? m : 1;
            for (int c = 0; c < kCols; ++c)
                for (int64_t j = 0; j < m; ++j)
                    mat[(size_t)(c * strides[(size_t)i] + j)] =
                        src[(size_t)(c * n_live + i)][(size_t)j];
            bases[(size_t)i] = mat.data();
        }
        Slot& slot2 = slots[(size_t)((it + 1) % depth)];
        for (int c = 0; c < kCols; ++c) dst[c] = slot2.cols[c].data();
        const int64_t got2 = anomod_stage_lanes_mat(
            rt, dst.data(), bases.data(), strides.data(), n_rows.data(),
            fills, kCols, n_live, lanes, width);
        if (got2 != (int64_t)kCols * lanes * width ||
            !verify(slot2, src, n_rows, fills, n_live, lanes, width))
            ++failures;
    }
}

int hammer(int n_workers, int iters, int depth, int32_t n_live,
           int64_t lanes, int64_t width) {
    void* rt = anomod_rt_create(2);     // shared pool: the race surface
    std::vector<std::thread> ts;
    for (int t = 0; t < n_workers; ++t)
        ts.emplace_back(worker, rt, t, iters, depth, n_live, lanes, width);
    for (auto& t : ts) t.join();
    anomod_rt_destroy(rt);
    return failures.load();
}

// ---- SFQ drain/shed kernels (PR 16) ---------------------------------------
//
// anomod_sfq_drain / anomod_sfq_victim are pure functions over caller-owned
// columns — the race-freedom claim is "no hidden shared/static state", so
// the hammer drives them from N concurrent threads, each on its own arrays,
// and checks the results against an independently-written O(n^2) reference
// (repeated min-scan selection for the drain; a separate max-scan pass for
// the victim).  Any cross-thread corruption breaks byte-parity; any shared
// internals trip TSan.

void sfq_worker(int tid, int iters, int64_t n) {
    uint32_t seed = 0x85ebca6bu * (uint32_t)(tid + 1);
    std::vector<double> fin((size_t)n);
    std::vector<int64_t> seq((size_t)n), nsp((size_t)n), pri((size_t)n);
    std::vector<uint8_t> alive((size_t)n);
    std::vector<int64_t> out((size_t)n), want((size_t)n);
    for (int it = 0; it < iters; ++it) {
        for (int64_t i = 0; i < n; ++i) {
            fin[(size_t)i] = (double)(lcg(seed) % 4096u) / 16.0;
            seq[(size_t)i] = i;          // unique, the tie-break contract
            nsp[(size_t)i] = 1 + (int64_t)(lcg(seed) % 200u);
            pri[(size_t)i] = (int64_t)(lcg(seed) % 3u);
            alive[(size_t)i] = (uint8_t)(lcg(seed) % 4u != 0);
        }
        const double budget = (double)(lcg(seed) % 2048u) + 0.5;
        const int64_t got = anomod_sfq_drain(
            fin.data(), seq.data(), nsp.data(), alive.data(), n, budget,
            out.data());
        // reference: repeated min-scan (selection sort, no std::sort) +
        // the same sequential budget walk
        std::vector<uint8_t> left(alive);
        double remaining = budget;
        int64_t n_want = 0;
        for (;;) {
            if (!(remaining > 0.0)) break;
            int64_t best = -1;
            for (int64_t i = 0; i < n; ++i) {
                if (!left[(size_t)i]) continue;
                if (best < 0 || fin[(size_t)i] < fin[(size_t)best] ||
                    (fin[(size_t)i] == fin[(size_t)best] &&
                     seq[(size_t)i] < seq[(size_t)best]))
                    best = i;
            }
            if (best < 0) break;
            left[(size_t)best] = 0;
            remaining -= (double)nsp[(size_t)best];
            want[(size_t)n_want++] = best;
        }
        if (got != n_want ||
            !std::equal(out.begin(), out.begin() + (size_t)n_want,
                        want.begin()))
            ++failures;
        const int64_t v = anomod_sfq_victim(
            fin.data(), seq.data(), pri.data(), alive.data(), n);
        int64_t vref = -1;
        for (int64_t i = 0; i < n; ++i) {
            if (!alive[(size_t)i]) continue;
            if (vref < 0 ||
                pri[(size_t)i] > pri[(size_t)vref] ||
                (pri[(size_t)i] == pri[(size_t)vref] &&
                 (fin[(size_t)i] > fin[(size_t)vref] ||
                  (fin[(size_t)i] == fin[(size_t)vref] &&
                   seq[(size_t)i] > seq[(size_t)vref]))))
                vref = i;
        }
        if (v != vref) ++failures;
    }
}

int sfq_hammer(int n_workers, int iters, int64_t n) {
    std::vector<std::thread> ts;
    for (int t = 0; t < n_workers; ++t)
        ts.emplace_back(sfq_worker, t, iters, n);
    for (auto& t : ts) t.join();
    return failures.load();
}

}  // namespace

int main(int argc, char** argv) {
    const int n_workers = argc > 1 ? std::atoi(argv[1]) : 4;
    const int iters = argc > 2 ? std::atoi(argv[2]) : 40;
    // small slots: the calling-thread fill path, many concurrent callers
    hammer(n_workers, iters, /*depth=*/3, /*n_live=*/3, /*lanes=*/4,
           /*width=*/64);
    // big slots (lanes*width >= 1<<16): the Runtime pool fan-out + Latch
    // path — per-column tasks from MULTIPLE staging calls interleave in
    // one queue, exactly the overlap the GIL-free path exists for
    hammer(n_workers, iters / 8 + 1, /*depth=*/2, /*n_live=*/6,
           /*lanes=*/8, /*width=*/8192);
    // admission-plane SFQ kernels: N threads drain/shed concurrently on
    // their own columns against an O(n^2) reference — byte-parity catches
    // corruption, TSan catches any hidden shared state
    sfq_hammer(n_workers, iters, /*n=*/512);
    const int f = failures.load();
    if (f) {
        std::fprintf(stderr, "sanitize_hammer: %d byte-parity failures\n",
                     f);
        return 2;
    }
    std::printf("sanitize_hammer ok\n");
    return 0;
}
