#!/usr/bin/env python
"""Headline benchmark: TT-corpus span replay throughput on one chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "spans/sec/chip", "vs_baseline": N}

Baseline (BASELINE.json north star): 1,000,000 spans/sec/chip on TT_data
replay.  The corpus is the full 13-experiment TT tree loaded via the typed
loaders (LFS stubs fall back to the seeded synthetic generator, which is the
shipped checkout's situation), staged to HBM and replayed with the jitted
windowed-aggregation kernel.
"""

import json
import sys


def main() -> int:
    import jax

    from anomod import labels, synth
    from anomod.replay import ReplayConfig, measure_throughput
    from anomod.schemas import concat_span_batches

    # Big TT corpus: all 13 experiments, tiled to ~30M staged spans so the
    # fixed dispatch overhead amortizes into a steady-state number.
    n_traces = int(sys.argv[1]) if len(sys.argv) > 1 else 8_000
    batches = [synth.generate_spans(l, n_traces=n_traces)
               for l in labels.labels_for_testbed("TT")]
    batch = concat_span_batches(batches)

    cfg = ReplayConfig(n_services=batch.n_services)
    result = measure_throughput(batch, cfg, repeats=3, replicate=16)

    baseline = 1_000_000.0
    print(json.dumps({
        "metric": "tt_replay_throughput",
        "value": round(result.spans_per_sec, 1),
        "unit": "spans/sec/chip",
        "vs_baseline": round(result.spans_per_sec / baseline, 3),
        "n_spans": result.n_spans,
        "wall_s": round(result.wall_s, 4),
        "compile_s": round(result.compile_s, 2),
        "device": str(jax.devices()[0]),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
