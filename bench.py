#!/usr/bin/env python
"""Headline benchmark: TT-corpus span replay throughput on one chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "spans/sec/chip", "vs_baseline": N}

Baseline (BASELINE.json north star): 1,000,000 spans/sec/chip on TT_data
replay.  The corpus is the full 13-experiment TT tree loaded via the typed
loaders (LFS stubs fall back to the seeded synthetic generator, which is the
shipped checkout's situation), staged to HBM once and replayed with the
jitted windowed-aggregation kernel; ``replicate`` loops the corpus on device
to reach steady state (~30M spans counted per dispatch on TPU).

Corpus prep reads through the content-addressed ingest cache
(anomod.io.cache; ``ANOMOD_CACHE_DIR``), so repeat captures measure the
kernel instead of re-synthesizing the corpus.  The JSON line reports the
split: ``prep_s`` (what this run paid), ``parse_s`` (the recorded cold
generate+concat wall), ``cache_hit``, and ``tt_ingest_throughput``
(experiments/sec cold vs warm) — see docs/BENCHMARKS.md.  Warm the cache
before driver captures with ``anomod ingest --warm-cache`` or gate on
``scripts/pre_bench_check.py``.

Environment hardening (the capture path must survive a dead axon tunnel,
where anything touching ``jax.devices()`` either raises or hangs forever):

  1. The device backend is probed in a *subprocess* with a hard deadline
     (bounded retry), so a hung tunnel cannot hang this process.
  2. On probe failure the benchmark pins ``jax_platforms=cpu`` before backend
     init (the same pre-init pin tests/conftest.py uses — env vars alone do
     not override the container sitecustomize's forced axon registration) and
     still produces a number, with ``device_note`` explaining the fallback.
  3. Any error after that still emits the JSON line with an ``error`` field.

``ANOMOD_BENCH_PLATFORM=cpu|tpu`` skips the probe and forces the platform.
The probe VERDICT is cached under ``ANOMOD_CACHE_DIR`` (keyed by
jax/jaxlib version + OS platform), so a CPU-only box pays the dead-tunnel
probe deadline once per install, not once per run; ``--probe-fresh``
bypasses the cache (use after a device tunnel revives).

Serve mode (``python bench.py --mode serve`` or ``ANOMOD_BENCH_MODE=serve``):
instead of the batch replay, drives the multi-tenant serving plane
(anomod.serve) with a seeded power-law fleet offering 2x the engine's
capacity and emits ONE JSON line with sustained spans/sec through
admission+batching+scoring, the p99 admission->scored latency, and the
shed fraction under that overload at the configured backlog budget —
plus a ``fused_dispatch`` block comparing the tenant-fused (lane-stacked)
path against one-dispatch-per-micro-batch on the same seed.
Gate serve captures on ``scripts/pre_bench_check.py --mode serve`` (bucket
set AND the (width x lane-bucket) fused grid must validate + compile).  Knobs: ``ANOMOD_SERVE_BENCH_CAPACITY``
(spans/sec, default 25000), ``ANOMOD_SERVE_BENCH_DURATION`` (virtual
seconds, default 60), ``ANOMOD_SERVE_BENCH_TENANTS`` (default 200).

Telemetry (anomod.obs, docs/OBSERVABILITY.md): both modes inline an
``obs_snapshot`` of the process registry in the JSON line; serve mode
additionally runs the same seed twice (telemetry on, then off — the off
leg inherits the process warmup, so the fraction is an upper bound) to
report the enabled-telemetry overhead (bar: <= 5%) and exports the
enabled leg's scrape journal as a TT-CSV self-scrape capture next to the
provenance record, scored through the framework's own detector stack.
"""

import json
import os
import sys
import time

def _resolve_platform(attempts=None, fresh=False):
    """Return ("default"|"cpu", diagnostic). Probes backend init out-of-process
    (anomod.utils.platform.probe_device_platform) with a hard deadline per
    attempt so a dead tunnel can't block the bench.  A backend that
    initializes but is CPU-only still resolves to "cpu" so the workload is
    sized for the host, not for a TPU.

    The verdict is CACHED under ``ANOMOD_CACHE_DIR`` keyed by
    jax/jaxlib version + OS platform, so a CPU-only box pays the probe
    deadline (up to ~60 s per attempt on a dead tunnel) once per
    install instead of once per run.  ``--probe-fresh`` bypasses the
    cache and re-probes (use after a device tunnel revives)."""
    forced = os.environ.get("ANOMOD_BENCH_PLATFORM", "").strip().lower()
    if forced:
        plat = "cpu" if forced == "cpu" else "default"
        return plat, f"forced via ANOMOD_BENCH_PLATFORM={forced}"
    from anomod.utils.platform import (env_number, probe_device_platform,
                                       read_probe_verdict,
                                       write_probe_verdict)
    cached = None if fresh else read_probe_verdict()
    if cached is not None and cached[0] not in ("", "cpu"):
        cached = None        # never trust a cached live-device verdict
    if cached is not None:
        plat, diag = cached
    else:
        plat, diag = probe_device_platform(attempts)
        # Bounded revival retry before conceding the CPU fallback: the axon
        # tunnel drops and revives on minute scales, so a driver capture that
        # lands in a dead window still has a chance to go on-chip.  Each extra
        # probe is a fresh 60 s-deadline subprocess, 30 s apart — ~5 min worst
        # case on top of the initial (75+30) s probe, then the fallback.
        retries = env_number("ANOMOD_BENCH_PROBE_RETRIES", 3)
        while not plat and retries > 0:
            time.sleep(30)
            plat, diag = probe_device_platform((60.0,))
            retries -= 1
            diag = f"{diag}; {retries} probe retries left"
        # the FINAL verdict (post-retry) is what the cache records — but
        # ONLY a CPU/timeout verdict.  Caching a live-accelerator verdict
        # would let a later run skip the liveness probe entirely and then
        # hang without a deadline at first backend touch when the tunnel
        # has died since — the exact failure the out-of-process probe
        # exists to prevent.  A CPU-only box's verdict cannot go stale
        # that way (there is no tunnel to die), which is the case the
        # cache is for.
        if plat in ("", "cpu"):
            write_probe_verdict(plat, diag)
    note = " [cached verdict; --probe-fresh re-probes]" \
        if cached is not None else ""
    if plat == "cpu":
        return "cpu", f"backend probe found CPU-only devices{note}"
    if plat:
        return "default", f"device backend probe ok ({plat}){note}"
    return "cpu", f"device backend unavailable ({diag}){note}"


def _bench_mode(argv) -> str:
    """"replay" (default) or "serve"; --mode beats ANOMOD_BENCH_MODE."""
    if "--mode" in argv:
        i = argv.index("--mode")
        if i + 1 >= len(argv):
            raise SystemExit("bench.py: --mode needs a value "
                             "(replay|serve)")
        mode = argv[i + 1].strip().lower()
    else:
        mode = os.environ.get("ANOMOD_BENCH_MODE", "replay").strip().lower()
    if mode not in ("replay", "serve"):
        raise SystemExit(f"bench.py: unknown mode {mode!r} (replay|serve)")
    return mode


def serve_main(probe_fresh=False) -> int:
    """The serve-mode capture: sustained spans/sec + p99 latency + shed
    fraction under a seeded 2x overload (fixed backlog budget).

    The run executes THREE times on the same seed: first with the
    self-scraping registry (anomod.obs) + default tracer on (the
    headline numbers, fused dispatch per the config default), then with
    telemetry forced off — the ``telemetry`` block reports both
    sustained rates and the enabled-telemetry overhead fraction
    (acceptance bar: <= 5%; the off leg runs second so it inherits the
    one-time process warmup and the fraction is an upper bound) — and
    then with the tenant-FUSED dispatch forced off (telemetry on,
    its own registry): the ``fused_dispatch`` block reports fused vs
    unfused sustained spans/sec, p99 and shed fraction on the same seed
    (the unfused leg runs after both headline legs so the speedup is
    never flattered by warmup order).  A PYTHON-STAGING leg (same seed,
    ``native=False``) then isolates the C++ GIL-free lane packing: the
    ``staging`` block decomposes the serve wall into stage / dispatch /
    fold / score / other for both legs — the serving-overhead gap
    attributed with numbers — plus the byte-parity bits (native staging
    is pinned byte-identical, so every decision metric must match
    exactly).  A HOST-SEAM state leg (same seed,
    ``ANOMOD_SERVE_STATE=host``) isolates the device-resident tenant
    pool the same way: the ``serve_state`` block carries both legs'
    five-way decompositions, the fold+score+other share the residency
    change attacks, and the pool's byte-parity bits.  A FLIGHT-OFF leg
    (same seed, ``flight=False``) prices the black-box tick journal
    (anomod.obs.flight): the ``flight`` block reports the recorder's
    overhead fraction (bar: <= 5%), its drop counters (zero = the ring
    never evicted) and the read-side byte-parity bits.  A CHAOS leg
    (scripted mid-run shard crashes, same seed) fills the ``recovery``
    block: checkpoint-cadence overhead measured in-run on the headline
    (ckpt_wall_s / serve_wall_s, bar: <= 5%), crash/restored-tick
    counts, and the no-score-gap parity bits (the chaos leg's
    states/alerts/p99/shed and canonical flight journal must equal the
    fault-free headline's).
    An ELASTICITY pair (sub-capacity load + a scripted ``surge`` chaos
    window, served static and again under ``ANOMOD_SERVE_POLICY=auto``)
    fills the ``elasticity`` block: scale-up/down episode counts, the
    migration volume, and the elastic determinism parity bits (the
    policy run's states/alerts/p99/shed and canonical flight journal
    must equal the static leg's).
    A PROCESS-WORKER quartet (ISSUE-20: 2-shard thread oracle, 2-shard
    and 1-shard process engines, and a dense-fold process reference,
    same seed) fills the ``proc_shard`` block: thread-vs-process and
    N-vs-1-process parity bits, the sparse barrier fold's payload
    bytes against the dense walk, and the per-leg raw_wall_s samples —
    throughput scaling quoted only when the box has >= 4 cores
    (``scaling_quotable``).
    After the shard-scaling legs,
    two ONLINE-RCA legs (1-shard and 2-shard, ``rca=True``, same seed)
    fill the ``rca`` block: top-k hit-rate (k=1,3,5) against the
    injected-fault ground truth, alert→culprit latency quantiles, and
    the determinism pins (RCA-on leaves alerts/states/p99/shed
    byte-identical; 2-shard verdicts equal 1-shard).  The enabled
    run's scrape journal is exported as a
    TT-CSV self-scrape capture next to the provenance record and scored
    through the framework's own detector stack (``self_scrape``
    block)."""
    from anomod.utils.platform import env_number
    out = {
        "metric": "serve_sustained_throughput",
        "value": 0.0,
        "unit": "spans/sec",
        "mode": "serve",
    }
    platform, diag = _resolve_platform(fresh=probe_fresh)
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    try:
        from anomod.obs.registry import Registry, set_registry
        from anomod.serve.engine import run_power_law
        from anomod.utils.platform import enable_jit_cache
        jit_cache_dir = enable_jit_cache()
        capacity = env_number("ANOMOD_SERVE_BENCH_CAPACITY", 25_000)
        duration = env_number("ANOMOD_SERVE_BENCH_DURATION", 60)
        tenants = env_number("ANOMOD_SERVE_BENCH_TENANTS", 200)
        run_kw = dict(
            n_tenants=int(tenants), n_services=12,
            capacity_spans_per_s=float(capacity), overload=2.0,
            duration_s=float(duration), tick_s=0.5, seed=7,
            window_s=5.0, baseline_windows=4, fault_tenants=2,
            # the fixed shed budget: 8 seconds of capacity worth of
            # backlog — scale-invariant, so a down-sized contract run
            # sheds in the same regime as the headline capture
            max_backlog=int(8 * float(capacity)))
        # telemetry-on leg FIRST (the headline numbers), telemetry-off
        # reference leg second: the second leg inherits every one-time
        # process warmup (allocator growth, first-touch code paths), so
        # the reported overhead fraction is an upper bound on what
        # telemetry actually costs — never flattered by run order
        # the headline leg pins shards=1: comparable with every prior
        # capture, and it doubles as leg 1 of the shard-scaling table
        reg = Registry(enabled=True)
        prev_reg = set_registry(reg)
        eng_head, rep = run_power_law(shards=1, **run_kw)
        set_registry(Registry(enabled=False))
        try:
            _, rep_off = run_power_law(shards=1, **run_kw)
            # the unfused reference leg: same seed, fused dispatch
            # forced OFF, telemetry on (matching the headline leg) but
            # in its OWN registry so the headline journal/snapshot stays
            # the headline run's.  Runs after both headline legs (only
            # the shard-scaling legs follow), so it inherits the
            # process warmup and the reported fused speedup is not
            # flattered by run order.
            set_registry(Registry(enabled=True))
            _, rep_unfused = run_power_law(fuse=False, shards=1, **run_kw)
            # the python-staging reference leg: same seed, the C++
            # GIL-free lane packing forced OFF (interpreter fill), own
            # registry, run after the headline legs so the native
            # speedup is never flattered by warmup order.  Output is
            # byte-identical by construction — the leg isolates the
            # STAGE wall, and its parity bits are recorded in the
            # capture itself.
            set_registry(Registry(enabled=True))
            eng_pystage, rep_pystage = run_power_law(
                native=False, shards=1, **run_kw)
            # the host-seam state reference leg: same seed, tenant
            # states kept as per-tenant numpy pytrees (the pre-pool
            # seam, ANOMOD_SERVE_STATE=host) with the per-lane fold
            # adds and per-tenant sequential window scoring — the
            # device-pool headline is pinned byte-identical, and this
            # leg's five-way wall decomposition is what the residency
            # change is measured against
            set_registry(Registry(enabled=True))
            eng_hostst, rep_hostst = run_power_law(
                state="host", shards=1, **run_kw)
            # the flight-recorder-off reference leg: same seed, the
            # black-box tick journal (anomod.obs.flight) forced OFF,
            # telemetry on, own registry, run after the headline legs
            # so the recorder's measured overhead is an upper bound.
            # The recorder is a pure read-side consumer, so every
            # decision metric must match the headline byte-for-byte —
            # the `flight` block records the parity bits with the
            # overhead (bar: <= 5%, the telemetry discipline)
            set_registry(Registry(enabled=True))
            eng_floff, rep_floff = run_power_law(
                flight=False, shards=1, **run_kw)
            # the CHAOS leg: same seed, scripted mid-run shard faults
            # (two worker kills, a score-path exception) under
            # supervision — the capture's own proof that recovery
            # leaves NO score gap: states/alerts/SLO/shed and the
            # canonical flight journal must equal the headline's.
            # Checkpoint overhead is measured DIRECTLY on the headline
            # (ckpt_wall_s / serve_wall_s — snapshot wall is accounted
            # inside the tick, so the fraction needs no A/B leg and is
            # immune to this box's run-to-run noise); real worker
            # respawn is exercised by the 2-shard pre-bench smoke.
            n_ticks = int(round(run_kw["duration_s"] / run_kw["tick_s"]))
            chaos_script = (
                f"crash@{n_ticks // 3}:shard=0:phase=dispatch;"
                f"except@{n_ticks // 2}:shard=0:phase=score;"
                f"crash@{(2 * n_ticks) // 3}:shard=0:phase=stage")
            set_registry(Registry(enabled=True))
            eng_chaos, rep_chaos = run_power_law(
                chaos=chaos_script, shards=1, **run_kw)
            # the shard-scaling legs (2 and 4 engine workers, same
            # seed), then a FRESH 1-shard reference leg LAST: the
            # reference inherits the most process warmup of the whole
            # capture, so speedup_vs_1_shard can only understate shard
            # scaling, never report warmup as speedup (the same
            # run-order discipline as the unfused leg above).  Each leg
            # gets its own registry; with ANOMOD_JIT_CACHE on the
            # per-shard compile grids hit the persistent cache.
            shard_reps = {}
            for n_shards in (2, 4, 1):
                set_registry(Registry(enabled=True))
                _, shard_reps[n_shards] = run_power_law(
                    shards=n_shards, **run_kw)
            # online-RCA legs (same seed, run LAST so the headline legs
            # never inherit their warmup): shards=1 with RCA on for the
            # alert→culprit product numbers, then a 2-shard RCA leg
            # whose verdict stream must be byte-identical — the capture
            # records the determinism checks it ran, not just numbers
            set_registry(Registry(enabled=True))
            eng_rca, rep_rca = run_power_law(shards=1, rca=True, **run_kw)
            set_registry(Registry(enabled=True))
            eng_rca2, _ = run_power_law(shards=2, rca=True, **run_kw)
            # the PERF leg: same seed, the dispatch-lifecycle timeline
            # (anomod.obs.perf) forced ON — the `perf` block carries
            # the overlap-headroom bound (the go/no-go instrument for
            # the fold-wait-overlap attack), the measured fold WAIT,
            # the on/off overhead fraction (bar: <= 5%, the telemetry/
            # flight discipline; the on leg runs after the headline so
            # the ratio inherits warmup like every A/B pair here), the
            # read-side parity bits, and the headline leg's per-tick
            # raw_wall_s samples `anomod perf diff` bootstraps over
            set_registry(Registry(enabled=True))
            eng_perf, rep_perf = run_power_law(perf=True, shards=1,
                                               **run_kw)
            # the ASYNC-COMMIT leg (ISSUE-16): same seed, the deferred-
            # commit tick forced ON with the perf recorder — tick N's
            # fold dispatch is issued un-waited, the coordinator runs
            # tick N+1's admission/drain/shed/SLO under the in-flight
            # XLA work, and the commit barrier lands just before the
            # results are first read.  Runs right after the perf leg
            # (its matched synchronous A side) so the hidden-wait
            # numbers inherit identical warmup; the parity bits are
            # the capture's own proof that the overlap moved only
            # wall-clock, never a scored byte.
            set_registry(Registry(enabled=True))
            eng_async, rep_async = run_power_law(
                async_commit=True, perf=True, shards=1, **run_kw)
            # the PROCESS-WORKER legs (ISSUE-20): the same seed served
            # four ways — 2 shard THREADS (the byte-parity oracle,
            # sparse fold), 2 shard PROCESSES (the GIL-free engine,
            # sparse fold), 1 shard process (the N-vs-1 process parity
            # side), and 2 shard processes under the DENSE barrier fold
            # (the sparse payload's reference walk).  The thread leg
            # runs FIRST so the process legs inherit its warmup and the
            # thread/process wall comparison is never flattered by run
            # order; every decision plane and the canonical flight
            # journal must be byte-identical across all four.
            set_registry(Registry(enabled=True))
            eng_pwt, rep_pwt = run_power_law(
                shards=2, worker="thread", fold="sparse", **run_kw)
            set_registry(Registry(enabled=True))
            eng_pwp, rep_pwp = run_power_law(
                shards=2, worker="process", fold="sparse", **run_kw)
            set_registry(Registry(enabled=True))
            eng_pw1, rep_pw1 = run_power_law(
                shards=1, worker="process", fold="sparse", **run_kw)
            set_registry(Registry(enabled=True))
            eng_pwd, rep_pwd = run_power_law(
                shards=2, worker="process", fold="dense", **run_kw)
            # the ELASTICITY legs: a sub-capacity fleet hit by a
            # scripted load surge (the chaos 'surge' kind), served
            # twice on the same seed — once static, once under the
            # signal-fed elastic policy (scale 1→2 into the surge, back
            # down after it).  The capture's own proof of the elastic
            # determinism contract: the policy run must produce ≥1
            # scale-up and ≥1 scale-down episode AND leave every
            # decision plane byte-identical to the static run — the
            # autoscaler moves wall-clock capacity around, never a
            # scored byte.
            elastic_kw = dict(run_kw)
            elastic_kw["overload"] = 0.6
            # an eighth-of-the-run surge: long enough to sustain the
            # scale-up hysteresis, short enough that the brownout
            # ladder never reaches level 2 (digest coarsening) — the
            # parity bit below compares canonical journals, and a
            # deliberately coarsened digest cadence would read as fold
            # divergence (the ladder has its own pinned test)
            surge_script = (f"surge@{n_ticks // 4}:factor=4:"
                            f"ticks={max(1, n_ticks // 8)}")
            set_registry(Registry(enabled=True))
            eng_els, rep_els = run_power_law(
                shards=1, chaos=surge_script, **elastic_kw)
            set_registry(Registry(enabled=True))
            eng_el, rep_el = run_power_law(
                shards=1, chaos=surge_script, policy="auto",
                min_shards=1, max_shards=2, cooldown_ticks=5,
                **elastic_kw)
            # the CENSUS leg (ISSUE-15): same seed, the fleet census
            # observatory (anomod.obs.census) forced ON — deterministic
            # resident-bytes per plane, the hot-set/Zipf census, the
            # read-side parity bits, and the on/off overhead fraction
            # (≤5% bar, the telemetry discipline)
            set_registry(Registry(enabled=True))
            eng_cen, rep_cen = run_power_law(census=True, shards=1,
                                             **run_kw)
            # the registered-fleet sweep: per-tick wall and resident-
            # bytes slopes vs the REGISTERED count at fixed ~1e3-hot
            # traffic — the committed O(registered) baseline curve the
            # million-tenant tiering refactor must flatten (`anomod
            # census diff` judges the before/after).  Own registry so
            # the probe engines' gauges stay out of the headline
            # journal.
            set_registry(Registry(enabled=True))
            from anomod.obs.census import fleet_probe
            census_sweep = fleet_probe()
            # the TIERING legs (ISSUE-19): (a) the registered-fleet
            # sweep re-run with the tenant-state tiering plane ON —
            # the same ~1e3-hot traffic against up to 1e6 REGISTERED
            # tenants, the O(hot-set) curve the committed PR-15
            # O(registered) baseline must collapse to (`anomod census
            # diff OLD NEW` judges the pair); (b) a sub-capacity
            # tiered-vs-never-evicted parity pair on the same seed —
            # sub-capacity because the power-law tail must idle whole
            # ticks for the decay plane to demote at all (an
            # overloaded fleet keeps every tenant backlogged and the
            # anti-thrash exclusion never fires); the tiny warm budget
            # pushes most demotions through the content-addressed
            # disk cold tier, so the counters below evidence all four
            # event legs (warm demote, cold spill, promote, miss) and
            # the prefetch lane.  Own registries throughout.
            import tempfile as _tempfile
            set_registry(Registry(enabled=True))
            # one extra 10x-the-max top point past the untiered sweep:
            # on the default 1e3/1e4/1e5 sweep that is the committed
            # capture's 1e6-registered / 1e3-hot mode; a down-sized
            # ANOMOD_CENSUS_SWEEP (the bench contract test) scales the
            # same shape without the minute-class top row
            _tier_sizes = [*census_sweep["sizes"],
                           10 * max(census_sweep["sizes"])]
            tiered_sweep = fleet_probe(
                sizes=_tier_sizes,
                tier_hot=1_000, tier_demote_after=2)
            tier_kw = dict(
                n_tenants=48, n_services=8,
                capacity_spans_per_s=800.0, overload=0.5,
                duration_s=24.0, tick_s=1.0, seed=7, window_s=5.0,
                baseline_windows=2, fault_tenants=2,
                buckets=(64, 256), lane_buckets=(1, 2, 4),
                max_backlog=6400, n_windows=16)
            set_registry(Registry(enabled=True))
            eng_toff, rep_toff = run_power_law(shards=1, **tier_kw)
            with _tempfile.TemporaryDirectory() as _tier_cold:
                set_registry(Registry(enabled=True))
                eng_ton, rep_ton = run_power_law(
                    shards=1, tier_hot=12, tier_demote_after=2,
                    tier_warm_bytes=4096, tier_cold_dir=_tier_cold,
                    tier_prefetch=2, **tier_kw)
                _tier_left = len(eng_ton._tier)
                _tier_joins = eng_ton._tier.prefetch_joins
            # the same-config rerun: a deferred cold fold legitimately
            # moves spans one tick later, so the tiered journal is NOT
            # tick-for-tick equal to the never-evicted twin's — the
            # journal determinism pin is instead that the SAME tiered
            # config replays byte-identically (what `anomod audit
            # replay` relies on)
            with _tempfile.TemporaryDirectory() as _tier_cold2:
                set_registry(Registry(enabled=True))
                eng_ton2, rep_ton2 = run_power_law(
                    shards=1, tier_hot=12, tier_demote_after=2,
                    tier_warm_bytes=4096, tier_cold_dir=_tier_cold2,
                    tier_prefetch=2, **tier_kw)
            # the LIVE-FEED leg (ISSUE-18): the closed telemetry loop —
            # an embedded /metrics endpoint serving THIS process's
            # registry, scraped by LiveFeed into the serve tick,
            # wire-journaled, then replayed through ReplayTransport.
            # Live-vs-replay byte parity is the --from-live
            # reproducibility pin.  Own registry so the loop scrapes a
            # stable, self-generated fleet.
            import tempfile as _tempfile

            from anomod.obs.http import ObsHttpServer
            from anomod.serve.feed import run_live_feed
            _feed_reg = Registry(enabled=True)
            set_registry(_feed_reg)
            _feed_kw = dict(capacity_spans_per_s=2000.0,
                            duration_s=10.0, tick_s=1.0, window_s=2.0,
                            baseline_windows=2, buckets=(64,),
                            n_windows=16, flight=True,
                            flight_digest_every=2)
            with _tempfile.TemporaryDirectory() as _ftmp, \
                    ObsHttpServer(port=0) as _fsrv:
                _fjournal = os.path.join(_ftmp, "feed_wire.json")
                eng_lf, rep_lf, feed_lf = run_live_feed(
                    scrape_url=f"{_fsrv.url}/metrics", n_tenants=4,
                    n_services=4, journal=_fjournal, **_feed_kw)
                _feed_journal_entries = len(feed_lf.journal_entries())
                _fsrv.stop()
                eng_lfr, rep_lfr, _ = run_live_feed(
                    replay=_fjournal, **_feed_kw)
        finally:
            set_registry(prev_reg)
        set_registry(reg)
        d = rep.to_dict()
        out.update({
            "value": rep.sustained_spans_per_sec,
            "p99_admission_to_scored_latency_s":
                rep.latency.get("p99_latency_s"),
            "p50_admission_to_scored_latency_s":
                rep.latency.get("p50_latency_s"),
            "shed_fraction": rep.shed_fraction,
            "offered_spans": rep.offered_spans,
            "served_spans": rep.served_spans,
            "overload": 2.0,
            "capacity_spans_per_s": rep.capacity_spans_per_s,
            "max_backlog": rep.max_backlog,
            "n_tenants": rep.n_tenants,
            "duration_virtual_s": rep.duration_s,
            "serve_wall_s": rep.serve_wall_s,
            "compile_s": rep.compile_s,
            "buckets": d["buckets"],
            "dispatches_by_width": d["dispatches_by_width"],
            "fault_detection": rep.fault_detection,
            "n_alerts": rep.n_alerts,
            "device": str(jax.devices()[0]),
        })
        # fused vs unfused on the same seed (both telemetry-on): the
        # tenant-fused lane-stacked dispatch against one dispatch per
        # tenant micro-batch
        out["fused_dispatch"] = {
            "fused": rep.fused,
            "spans_per_sec_fused": rep.sustained_spans_per_sec,
            "spans_per_sec_unfused": rep_unfused.sustained_spans_per_sec,
            "speedup": round(rep.sustained_spans_per_sec
                             / max(rep_unfused.sustained_spans_per_sec,
                                   1e-9), 2),
            "p99_latency_s_unfused":
                rep_unfused.latency.get("p99_latency_s"),
            "shed_fraction_unfused": rep_unfused.shed_fraction,
            "fused_dispatches": rep.fused_dispatches,
            "lane_buckets": list(rep.lane_buckets),
            "lanes_by_bucket": {str(k): v for k, v
                                in rep.lanes_by_bucket.items()},
            "lane_pad_waste": rep.lane_pad_waste,
            "lane_compile_s": rep.lane_compile_s,
        }
        # the serve-tick wall DECOMPOSITION (the serving-overhead gap,
        # attributed with numbers): host packing (stage) vs executable
        # issue (dispatch) vs output materialization + state folds
        # (fold), native vs interpreter staging legs on the same seed —
        # `other` is what the serve wall spends in admission/detector/
        # bookkeeping Python, the remaining interpreter tax
        import numpy as _np
        from anomod.io import native as _native
        _nat_status = _native.status()

        def _decomp(r):
            walls = {"stage": r.stage_wall_s, "dispatch": r.dispatch_wall_s,
                     "fold": r.fold_wall_s, "score": r.score_wall_s}
            walls["other"] = round(
                max(0.0, r.serve_wall_s - sum(walls.values())), 4)
            walls["serve"] = r.serve_wall_s
            return walls

        def _fso_share(r):
            """fold+score+other share of the serve wall — the serving-
            overhead gap's remaining interpreter/fold tax (the ISSUE-8
            acceptance number)."""
            w = _decomp(r)
            return round((w["fold"] + w["score"] + w["other"])
                         / max(w["serve"], 1e-9), 4)

        def _engines_identical(eng_a, eng_b):
            """(alerts_same, states_same) over the union of the two
            engines' tenants — the one definition every parity bit in
            this capture reads (staging and RCA legs alike)."""
            tids = sorted(set(eng_a._tenant_det) | set(eng_b._tenant_det))
            alerts = all(eng_a.alerts_for(t) == eng_b.alerts_for(t)
                         for t in tids)
            states = all(
                t in eng_a._tenant_replay and t in eng_b._tenant_replay
                and _np.array_equal(
                    _np.asarray(eng_a._tenant_replay[t].state.agg),
                    _np.asarray(eng_b._tenant_replay[t].state.agg))
                and _np.array_equal(
                    _np.asarray(eng_a._tenant_replay[t].state.hist),
                    _np.asarray(eng_b._tenant_replay[t].state.hist))
                for t in tids)
            return alerts, states

        _stage_alerts_same, _stage_states_same = _engines_identical(
            eng_head, eng_pystage)
        out["staging"] = {
            "native_mode": _nat_status["mode"],
            "native_available": _nat_status["available"],
            "build_error": _nat_status["build_error"],
            "native_staging_headline": rep.native_staging,
            "native_staged_dispatches": rep.native_staged_dispatches,
            "wall_s_native": _decomp(rep),
            "wall_s_python": _decomp(rep_pystage),
            "spans_per_sec_native": rep.sustained_spans_per_sec,
            "spans_per_sec_python": rep_pystage.sustained_spans_per_sec,
            "stage_wall_speedup": round(
                rep_pystage.stage_wall_s / max(rep.stage_wall_s, 1e-9), 2),
            "parity": {
                "alerts_identical": _stage_alerts_same,
                "states_identical": _stage_states_same,
                "p99_identical": rep_pystage.latency.get("p99_latency_s")
                == rep.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_pystage.shed_fraction == rep.shed_fraction,
            },
        }
        # tenant-state residency (ISSUE-8): the device-pool headline vs
        # the host-seam reference on the same seed — five-leg wall
        # decomposition, the fold+score+other share the residency
        # change attacks, and the byte-parity bits the pool is pinned
        # to (states, alerts, p99, shed — the pool performs the exact
        # same f32 adds, so every bit must match)
        _st_alerts_same, _st_states_same = _engines_identical(
            eng_head, eng_hostst)
        out["serve_state"] = {
            "headline": rep.serve_state,
            "pool_engine": (eng_head.runner.pool.engine
                            if eng_head.runner.pool is not None else None),
            "wall_s_device": _decomp(rep),
            "wall_s_host_seam": _decomp(rep_hostst),
            "fold_score_other_share_device": _fso_share(rep),
            "fold_score_other_share_host_seam": _fso_share(rep_hostst),
            "spans_per_sec_device": rep.sustained_spans_per_sec,
            "spans_per_sec_host_seam": rep_hostst.sustained_spans_per_sec,
            "parity": {
                "alerts_identical": _st_alerts_same,
                "states_identical": _st_states_same,
                "p99_identical": rep_hostst.latency.get("p99_latency_s")
                == rep.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_hostst.shed_fraction == rep.shed_fraction,
            },
        }
        # flight recorder (ISSUE-9): the always-on tick journal's
        # measured overhead on the same seed, its drop counters (zero =
        # no silent loss — the ring never evicted), and the byte-parity
        # bits a read-side recorder must hold against the no-recorder
        # leg
        _fl_alerts_same, _fl_states_same = _engines_identical(
            eng_head, eng_floff)
        out["flight"] = {
            "enabled_headline": rep.flight_enabled,
            "recorded_ticks": rep.flight_recorded_ticks,
            "dropped_ticks": rep.flight_dropped_ticks,
            "digest_every": (eng_head.flight_recorder.digest_every
                             if eng_head.flight_recorder is not None
                             else None),
            "spans_per_sec_on": rep.sustained_spans_per_sec,
            "spans_per_sec_off": rep_floff.sustained_spans_per_sec,
            "overhead_fraction": round(max(
                0.0, 1.0 - rep.sustained_spans_per_sec
                / max(rep_floff.sustained_spans_per_sec, 1e-9)), 4),
            "parity": {
                "alerts_identical": _fl_alerts_same,
                "states_identical": _fl_states_same,
                "p99_identical": rep_floff.latency.get("p99_latency_s")
                == rep.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_floff.shed_fraction == rep.shed_fraction,
            },
        }
        # chaos-hardened recovery (ISSUE-10): the checkpoint cadence
        # priced IN-RUN on the headline (ckpt_wall / serve_wall — no
        # A/B leg, see the block comment above the chaos leg), and the
        # chaos leg's in-capture proof that scripted mid-tick crashes
        # leave NO score gap — states/alerts/p99/shed byte-identical
        # to the fault-free headline and the canonical flight journals
        # equal under `anomod audit diff` semantics
        from anomod.obs.flight import diff_journals as _diff_journals
        _rc_alerts_same, _rc_states_same = _engines_identical(
            eng_head, eng_chaos)
        # the parity bit must be None (unknown), never vacuously true,
        # when no journals exist to compare (ANOMOD_FLIGHT=0 runs)
        _rc_journal_ok = None
        if eng_head.flight_recorder is not None \
                and eng_chaos.flight_recorder is not None:
            _rc_journal_ok = _diff_journals(
                eng_head.flight_recorder.journal(),
                eng_chaos.flight_recorder.journal()) is None
        out["recovery"] = {
            "supervised_headline": rep.supervised,
            "ckpt_every": rep.ckpt_every,
            "n_checkpoints": rep.n_checkpoints,
            "ckpt_wall_s": rep.ckpt_wall_s,
            # snapshot wall as a fraction of the headline serve wall —
            # the checkpoint-cadence overhead, measured in-run (the
            # snapshot is inside the tick wall, so this is exact; an
            # A/B leg would only add this box's ±35% noise on top)
            "ckpt_overhead_fraction": round(
                rep.ckpt_wall_s / max(rep.serve_wall_s, 1e-9), 4),
            "chaos_script": chaos_script,
            "n_shard_crashes": rep_chaos.n_shard_crashes,
            "n_respawns": rep_chaos.n_respawns,
            "n_restored_ticks": rep_chaos.n_restored_ticks,
            "n_quarantined": rep_chaos.n_quarantined,
            "n_migrated_tenants": rep_chaos.n_migrated_tenants,
            # mean ticks re-executed per recovery incident — how deep
            # into the checkpoint window the crashes landed (recovery
            # completes within the failing tick, so virtual-time MTTR
            # is bounded by one tick; this is the re-execution depth)
            "mttr_ticks": round(rep_chaos.n_restored_ticks
                                / max(rep_chaos.n_shard_crashes, 1), 2),
            "recovery_wall_s": rep_chaos.recovery_wall_s,
            "parity": {
                "alerts_identical": _rc_alerts_same,
                "states_identical": _rc_states_same,
                "p99_identical": rep_chaos.latency.get("p99_latency_s")
                == rep.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_chaos.shed_fraction == rep.shed_fraction,
                "journal_canonical_identical": _rc_journal_ok,
            },
        }
        # shard scaling on the same seed (1 / 2 / 4 engine workers; the
        # 1-shard row is the dedicated warm REFERENCE leg, run last).
        # Decision parity across legs is pinned by tests; the table
        # reports the wall-clock effect alone.  p99/shed are identical
        # across legs by construction (admission is shard-count-
        # invariant) — reported per leg anyway so the capture shows it.
        ref_sps = shard_reps[1].sustained_spans_per_sec
        out["shard_scaling"] = {
            str(n): {
                "spans_per_sec": r.sustained_spans_per_sec,
                "serve_wall_s": r.serve_wall_s,
                "speedup_vs_1_shard": round(
                    r.sustained_spans_per_sec / max(ref_sps, 1e-9), 3),
                "p99_latency_s": r.latency.get("p99_latency_s"),
                "shed_fraction": r.shed_fraction,
                "pipeline": r.pipeline,
                "shard_imbalance": r.shard_imbalance,
                "compile_s": round(r.compile_s + r.lane_compile_s, 4),
            } for n, r in sorted(shard_reps.items())}
        # saved-compile estimate: the slowest per-runner grid wall seen
        # in this run stands in for the cold compile (exact when any
        # runner was cold; an undercount on a fully warm cache, where
        # the savings landed before this run — lower bound either way)
        per_grid = [(r.compile_s + r.lane_compile_s) / n
                    for n, r in shard_reps.items()]
        cold_est = max(per_grid)
        out["jit_cache"] = {
            "enabled": jit_cache_dir is not None,
            "dir": jit_cache_dir,
            "grid_compile_s_per_runner": [round(g, 3) for g in per_grid],
            "saved_compile_s_lower_bound": round(sum(
                max(0.0, cold_est * n - (r.compile_s + r.lane_compile_s))
                for n, r in shard_reps.items()), 4)
            if jit_cache_dir is not None else 0.0,
        }
        # online RCA on the same seed: top-k hit-rate against the
        # traffic script's injected-fault ground truth, alert→culprit
        # latency (RCA runs in the same wall tick its alert fires, so
        # the per-run wall IS the alert→culprit wall), and the
        # determinism pins — RCA-on must leave every detector decision
        # byte-identical to the RCA-off headline leg, and the 2-shard
        # verdict stream must equal the 1-shard one
        alerts_same, states_same = _engines_identical(eng_head, eng_rca)
        n_fault = (rep_rca.fault_detection or {}).get("n_fault_tenants", 0)
        out["rca"] = {
            "enabled": True,
            "n_rca_runs": rep_rca.n_rca_runs,
            "topk_hits": {str(k): v for k, v
                          in sorted(rep_rca.rca_topk_hits.items())},
            "topk_hit_rate": {
                str(k): (round(v / n_fault, 4) if n_fault else None)
                for k, v in sorted(rep_rca.rca_topk_hits.items())},
            # conditional on the detector having fired for the fault
            # tenant at all — separates RCA ranking quality from the
            # detection recall ceiling it inherits (a fault tenant whose
            # spans mostly shed may never alert; that miss belongs to
            # the detection/shedding story, not to culprit ranking)
            "topk_hit_rate_given_detected": {
                str(k): (round(v / rep_rca.rca_eligible, 4)
                         if rep_rca.rca_eligible else None)
                for k, v in sorted(rep_rca.rca_topk_hits.items())},
            "eligible_fault_tenants": rep_rca.rca_eligible,
            "n_fault_tenants": n_fault,
            "alert_to_culprit_latency_s": rep_rca.rca_latency,
            "queue_delay_virtual_s": rep_rca.rca_alert_to_culprit_s,
            "rca_wall_s": rep_rca.rca_wall_s,
            "spans_per_sec_rca_on": rep_rca.sustained_spans_per_sec,
            "rca_overhead_fraction": round(max(
                0.0, 1.0 - rep_rca.sustained_spans_per_sec
                / max(rep.sustained_spans_per_sec, 1e-9)), 4),
            "parity": {
                "alerts_identical_to_rca_off": alerts_same,
                "states_identical_to_rca_off": states_same,
                "p99_identical_to_rca_off":
                    rep_rca.latency.get("p99_latency_s")
                    == rep.latency.get("p99_latency_s"),
                "shed_identical_to_rca_off":
                    rep_rca.shed_fraction == rep.shed_fraction,
                "verdicts_identical_1_vs_2_shards":
                    [v.to_dict() for v in eng_rca.rca_verdicts]
                    == [v.to_dict() for v in eng_rca2.rca_verdicts],
            },
        }
        # the performance observatory (ISSUE-14): the dispatch-lifecycle
        # timeline's overlap-bubble analysis on the same seed — the
        # overlap-headroom bound is the go/no-go instrument for ROADMAP
        # attack (1) (overlap the fold wait behind next-round staging),
        # the overhead fraction prices the recorder (≤5% bar), the
        # parity bits pin the read-side contract, and the raw_wall_s
        # per-tick samples are what `anomod perf diff` bootstraps over
        # instead of hedging wall ratios in prose
        from anomod.config import get_config as _get_config
        _pf_alerts_same, _pf_states_same = _engines_identical(
            eng_head, eng_perf)
        out["perf"] = {
            "enabled_headline": rep.perf_enabled,
            "events_recorded": rep_perf.perf_events_recorded,
            "events_dropped": eng_perf.perf_events_dropped,
            "overlap_headroom_s": rep_perf.overlap_headroom_s,
            "fold_wait_s": rep_perf.fold_wait_s,
            "fold_wall_s": rep_perf.fold_wall_s,
            "bubble_fractions": rep_perf.bubble_fractions,
            # the headline leg's per-tick serve walls: the matched-leg
            # sample list noise-aware capture diffing pairs by path
            "raw_wall_s": [round(t, 6) for t in eng_head.tick_walls],
            "perf_leg": {"raw_wall_s": [round(t, 6)
                                        for t in eng_perf.tick_walls]},
            "noise_floor": _get_config().perf_noise_floor,
            "spans_per_sec_on": rep_perf.sustained_spans_per_sec,
            "spans_per_sec_off": rep.sustained_spans_per_sec,
            "overhead_fraction": round(max(
                0.0, 1.0 - rep_perf.sustained_spans_per_sec
                / max(rep.sustained_spans_per_sec, 1e-9)), 4),
            "parity": {
                "alerts_identical": _pf_alerts_same,
                "states_identical": _pf_states_same,
                "p99_identical": rep_perf.latency.get("p99_latency_s")
                == rep.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_perf.shed_fraction == rep.shed_fraction,
            },
        }
        # the deferred-commit serve tick (ISSUE-16): the async leg vs
        # its matched synchronous perf leg — the committed fold WAIT
        # collapsing out of the serve wall (the `commit_defer` perf leg
        # carries where it went), with states/alerts/p99/shed and the
        # canonical flight journal pinned byte-identical.  The per-tick
        # raw_wall_s sample list is what `anomod perf diff` bootstraps
        # over to judge the overlap noise-aware.
        _as_alerts_same, _as_states_same = _engines_identical(
            eng_perf, eng_async)
        _as_journal_ok = None
        if eng_perf.flight_recorder is not None \
                and eng_async.flight_recorder is not None:
            _as_journal_ok = _diff_journals(
                eng_perf.flight_recorder.journal(),
                eng_async.flight_recorder.journal()) is None
        out["async_commit"] = {
            "enabled_headline": rep.async_commit,
            "async_ticks": rep_async.async_ticks,
            "commit_defer_wall_s": rep_async.commit_defer_wall_s,
            "fold_wait_s_sync": rep_perf.fold_wait_s,
            "fold_wait_s_async": rep_async.fold_wait_s,
            "fold_wait_hidden_fraction": round(max(
                0.0, 1.0 - rep_async.fold_wait_s
                / max(rep_perf.fold_wait_s, 1e-9)), 4),
            "serve_wall_s_sync": rep_perf.serve_wall_s,
            "serve_wall_s_async": rep_async.serve_wall_s,
            "spans_per_sec_sync": rep_perf.sustained_spans_per_sec,
            "spans_per_sec_async": rep_async.sustained_spans_per_sec,
            "speedup": round(rep_async.sustained_spans_per_sec
                             / max(rep_perf.sustained_spans_per_sec,
                                   1e-9), 2),
            "async_leg": {"raw_wall_s": [round(t, 6) for t
                                         in eng_async.tick_walls]},
            "parity": {
                "alerts_identical": _as_alerts_same,
                "states_identical": _as_states_same,
                "p99_identical": rep_async.latency.get("p99_latency_s")
                == rep_perf.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_async.shed_fraction == rep_perf.shed_fraction,
                "journal_canonical_identical": _as_journal_ok,
            },
        }
        # process-shard serving (ISSUE-20): the GIL-free worker engine
        # vs its matched 2-shard thread leg, the sparse barrier fold's
        # payload bytes vs the dense walk, and the determinism parity
        # bits — alerts compared tenant-by-tenant over the coordinator
        # mirrors, states pinned through the canonical flight journal's
        # state digests (a process engine's replay planes live in its
        # children; the journal digest IS the whole-fleet state bit).
        # Throughput scaling is quoted ONLY on a >= 4-core box: on two
        # cores the coordinator and two workers contend for the same
        # silicon and a speedup number would be noise, not signal —
        # `scaling_quotable` records which side this capture is on.
        import os as _os
        _n_cores = _os.cpu_count() or 1

        def _alerts_identical(eng_a, eng_b):
            tids = sorted(set(eng_a._tenant_det)
                          | set(eng_b._tenant_det))
            return all(eng_a.alerts_for(t) == eng_b.alerts_for(t)
                       for t in tids)

        def _pw_journal_bit(eng_a, eng_b):
            if eng_a.flight_recorder is None \
                    or eng_b.flight_recorder is None:
                return None
            return _diff_journals(
                eng_a.flight_recorder.journal(),
                eng_b.flight_recorder.journal()) is None

        out["proc_shard"] = {
            "worker_headline": rep.worker,
            "fold_headline": rep.fold,
            "n_cores": _n_cores,
            "scaling_quotable": _n_cores >= 4,
            "spans_per_sec_thread_2shard":
                rep_pwt.sustained_spans_per_sec,
            "spans_per_sec_process_2shard":
                rep_pwp.sustained_spans_per_sec,
            "spans_per_sec_process_1shard":
                rep_pw1.sustained_spans_per_sec,
            "speedup_process_vs_thread": (round(
                rep_pwp.sustained_spans_per_sec
                / max(rep_pwt.sustained_spans_per_sec, 1e-9), 2)
                if _n_cores >= 4 else None),
            "wall_s_thread": _decomp(rep_pwt),
            "wall_s_process": _decomp(rep_pwp),
            "fold_payload_bytes_sparse": rep_pwp.fold_payload_bytes,
            "fold_payload_bytes_dense": rep_pwd.fold_payload_bytes,
            "fold_payload_ratio": round(
                rep_pwp.fold_payload_bytes
                / max(rep_pwd.fold_payload_bytes, 1), 4),
            "thread_leg": {"raw_wall_s": [round(t, 6) for t
                                          in eng_pwt.tick_walls]},
            "process_leg": {"raw_wall_s": [round(t, 6) for t
                                           in eng_pwp.tick_walls]},
            "parity": {
                "alerts_identical_thread_vs_process":
                    _alerts_identical(eng_pwt, eng_pwp),
                "alerts_identical_2_vs_1_process":
                    _alerts_identical(eng_pwp, eng_pw1),
                "p99_identical": rep_pwp.latency.get("p99_latency_s")
                == rep_pwt.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_pwp.shed_fraction == rep_pwt.shed_fraction,
                "served_identical":
                    rep_pwp.served_spans == rep_pwt.served_spans,
                "journal_canonical_identical_thread_vs_process":
                    _pw_journal_bit(eng_pwt, eng_pwp),
                "journal_canonical_identical_2_vs_1_process":
                    _pw_journal_bit(eng_pwp, eng_pw1),
                "journal_canonical_identical_sparse_vs_dense":
                    _pw_journal_bit(eng_pwp, eng_pwd),
            },
        }
        # elastic serving (ISSUE-13): the policy leg's scaling episodes
        # under the scripted surge, the migration volume, the shard
        # imbalance the run ended on, and the determinism parity bits —
        # states/alerts/p99/shed byte-identical to the static leg of
        # the same seed+surge, canonical flight journals equal under
        # `anomod audit diff` semantics
        _el_alerts_same, _el_states_same = _engines_identical(
            eng_els, eng_el)
        _el_journal_ok = None
        if eng_els.flight_recorder is not None \
                and eng_el.flight_recorder is not None:
            _el_journal_ok = _diff_journals(
                eng_els.flight_recorder.journal(),
                eng_el.flight_recorder.journal()) is None
        _el_events = [ev for t in (eng_el.flight_recorder.records()
                                   if eng_el.flight_recorder is not None
                                   else [])
                      for ev in t.get("scaling", ())]
        out["elasticity"] = {
            "policy": rep_el.policy,
            "chaos_script": surge_script,
            "min_shards": 1, "max_shards": 2, "cooldown_ticks": 5,
            "n_scale_ups": rep_el.n_scale_ups,
            "n_scale_downs": rep_el.n_scale_downs,
            "n_rebalances": rep_el.n_rebalances,
            "n_policy_migrations": rep_el.n_policy_migrations,
            "migrated_spans": eng_el.policy_migrated_spans,
            "brownout_ticks": rep_el.brownout_ticks,
            "peak_shards": rep_el.peak_shards,
            "final_shards": rep_el.shards,
            "policy_wall_s": rep_el.policy_wall_s,
            "shard_imbalance_static": rep_els.shard_imbalance,
            "shard_imbalance_elastic": rep_el.shard_imbalance,
            "episodes": [{"kind": ev.get("kind"),
                          "tick": ev.get("tick"),
                          "tenants": ev.get("tenants", 0)}
                         for ev in _el_events],
            "spans_per_sec_static": rep_els.sustained_spans_per_sec,
            "spans_per_sec_elastic": rep_el.sustained_spans_per_sec,
            "parity": {
                "alerts_identical": _el_alerts_same,
                "states_identical": _el_states_same,
                "p99_identical": rep_el.latency.get("p99_latency_s")
                == rep_els.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_el.shed_fraction == rep_els.shed_fraction,
                "journal_canonical_identical": _el_journal_ok,
            },
        }
        # fleet census (ISSUE-15): the deterministic resident-bytes and
        # hot-set/Zipf census on the same seed, the registered-fleet
        # sweep's fitted O(registered) wall and bytes slopes (the
        # tiering baseline), one INFORMATIONAL /proc RSS sample beside
        # the deterministic total (cross-check only — never a pin,
        # never compared), and the read-side parity bits
        from anomod.obs.census import process_resident_bytes
        _cn_alerts_same, _cn_states_same = _engines_identical(
            eng_head, eng_cen)
        _cn_journal_ok = None
        if eng_head.flight_recorder is not None \
                and eng_cen.flight_recorder is not None:
            _cn_journal_ok = _diff_journals(
                eng_head.flight_recorder.journal(),
                eng_cen.flight_recorder.journal()) is None
        out["census"] = {
            "enabled_headline": rep.census_enabled,
            "census_ticks": rep_cen.census_ticks,
            "census_every": eng_cen.census_every,
            "resident_bytes": rep_cen.census_resident_bytes,
            "hot_set": rep_cen.census_hot_set,
            # ONE informational RSS sample: the order-of-magnitude
            # cross-check on the deterministic total above — never a
            # pin (allocator/runtime memory moves run to run)
            "process_resident_memory_bytes": process_resident_bytes(),
            "sweep": census_sweep,
            # census overhead measured IN-RUN (census_wall / serve_wall
            # — the ckpt_wall idiom: the drain is timed inside the
            # tick, so the fraction is exact and immune to this box's
            # ±35% A/B leg noise; acceptance bar: <= 5%).  The A/B
            # spans/sec pair below is recorded informationally.
            "census_wall_s": rep_cen.census_wall_s,
            "census_overhead_in_run": round(
                rep_cen.census_wall_s
                / max(rep_cen.serve_wall_s, 1e-9), 4),
            "spans_per_sec_on": rep_cen.sustained_spans_per_sec,
            "spans_per_sec_off": rep.sustained_spans_per_sec,
            "overhead_fraction": round(max(
                0.0, 1.0 - rep_cen.sustained_spans_per_sec
                / max(rep.sustained_spans_per_sec, 1e-9)), 4),
            "parity": {
                "alerts_identical": _cn_alerts_same,
                "states_identical": _cn_states_same,
                "p99_identical": rep_cen.latency.get("p99_latency_s")
                == rep.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_cen.shed_fraction == rep.shed_fraction,
                "journal_canonical_identical": _cn_journal_ok,
            },
        }
        # state tiering (ISSUE-19): the tiered registered-fleet sweep
        # (device hot pool → host warm tier → content-addressed disk
        # cold tier) beside the untiered census baseline above, the
        # demote/spill/promote/miss counters and prefetch-hidden
        # fraction from the sub-capacity parity pair, and the parity
        # bits — the capture's own proof that tiering moved only
        # resident bytes and wall-clock, never a scored byte.  The
        # journal bit compares the tiered run against its SAME-config
        # rerun (deferred cold folds move tick placement vs the
        # never-evicted twin, deterministically — that determinism IS
        # the audit-replay pin).
        _tr_alerts_same, _tr_states_same = _engines_identical(
            eng_toff, eng_ton)
        _tr_journal_ok = None
        if eng_ton.flight_recorder is not None \
                and eng_ton2.flight_recorder is not None:
            _tr_journal_ok = _diff_journals(
                eng_ton.flight_recorder.journal(),
                eng_ton2.flight_recorder.journal()) is None
        out["tiering"] = {
            "tier_hot": rep_ton.tier_hot,
            "sweep": tiered_sweep,
            # the committed-baseline collapse, restated locally: the
            # tiered sweep's deterministic bytes slope vs THIS
            # capture's untiered sweep (the cross-capture judgement —
            # 384 B/registered on the PR-15 curve — is `anomod census
            # diff OLD NEW`'s job)
            "bytes_slope_per_registered":
                tiered_sweep["bytes_slope_per_registered"],
            "wall_slope_s_per_registered":
                tiered_sweep["wall_slope_s_per_registered"],
            "baseline_bytes_slope_per_registered":
                census_sweep["bytes_slope_per_registered"],
            "counters": {
                "demotions_warm": rep_ton.n_tier_demotions_warm,
                "demotions_cold": rep_ton.n_tier_demotions_cold,
                "promotions": rep_ton.n_tier_promotions,
                "tier_misses": rep_ton.n_tier_misses,
            },
            "prefetch_hidden": rep_ton.tier_prefetch_hidden,
            "prefetch_joins": _tier_joins,
            "prefetch_hidden_fraction": round(
                rep_ton.tier_prefetch_hidden / max(_tier_joins, 1), 4),
            "tier_wall_s": rep_ton.tier_wall_s,
            "tier_empty_at_end": _tier_left == 0,
            "parity": {
                "alerts_identical": _tr_alerts_same,
                "states_identical": _tr_states_same,
                "p99_identical": rep_ton.latency.get("p99_latency_s")
                == rep_toff.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_ton.shed_fraction == rep_toff.shed_fraction,
                "served_identical":
                    rep_ton.served_spans == rep_toff.served_spans,
                "journal_rerun_identical": _tr_journal_ok,
            },
        }
        # live-feed loop (ISSUE-18): closed-loop self-scrape throughput,
        # the feed-lag histogram, and the live-vs-replay parity bits —
        # all five true is the --from-live reproducibility pin the
        # committed capture carries
        _lf_alerts_same, _lf_states_same = _engines_identical(
            eng_lf, eng_lfr)
        _lf_journal_ok = None
        if eng_lf.flight_recorder is not None \
                and eng_lfr.flight_recorder is not None:
            _lf_journal_ok = _diff_journals(
                eng_lf.flight_recorder.journal(),
                eng_lfr.flight_recorder.journal()) is None
        _lf_lag = next((m for m in _feed_reg.metrics()
                        if m.name == "anomod_feed_lag_s"), None)
        out["live_feed"] = {
            "spans_per_s": rep_lf.sustained_spans_per_sec,
            "served_spans": rep_lf.served_spans,
            "n_polls": feed_lf.n_polls,
            "n_samples": feed_lf.n_samples,
            "gaps": feed_lf.n_gaps,
            "feed_lag": {
                "p50": None if _lf_lag is None else _lf_lag.quantile(0.5),
                "p99": None if _lf_lag is None else _lf_lag.quantile(0.99),
            },
            "journal_entries": _feed_journal_entries,
            "parity": {
                "alerts_identical": _lf_alerts_same,
                "states_identical": _lf_states_same,
                "p99_identical": rep_lfr.latency.get("p99_latency_s")
                == rep_lf.latency.get("p99_latency_s"),
                "shed_identical":
                    rep_lfr.shed_fraction == rep_lf.shed_fraction,
                "journal_canonical_identical": _lf_journal_ok,
            },
        }
        # enabled-vs-off telemetry overhead on the same seed (acceptance
        # bar: <= 5% sustained spans/sec); both rates are steady-state
        # serving walls with compile excluded by warm()
        off_sps = rep_off.sustained_spans_per_sec
        on_sps = rep.sustained_spans_per_sec
        out["telemetry"] = {
            "spans_per_sec_off": off_sps,
            "spans_per_sec_on": on_sps,
            "overhead_fraction": round(max(0.0, 1.0 - on_sps
                                           / max(off_sps, 1e-9)), 4),
            "journal_samples": reg.n_samples,
        }
        out["obs_snapshot"] = reg.snapshot()
        if platform == "cpu":
            out["device_note"] = diag
        try:
            from anomod.provenance import capture_record, write_capture
            rec = capture_record(out["metric"], out["value"], out["unit"],
                                 **{k: v for k, v in out.items()
                                    if k not in ("metric", "value", "unit")})
            path = write_capture(rec)
            if path:
                out["capture_file"] = os.path.relpath(
                    path, os.path.dirname(os.path.abspath(__file__)))
                # the committed self-scrape capture: the enabled leg's
                # telemetry timeline in the framework's own TT-CSV shape,
                # scored through its own detector stack
                try:
                    from anomod.obs.export import export_tt_csv
                    from anomod.obs.selfscrape import score_self_scrape
                    csv_path = path[:-len(".json")] + "_selfscrape.csv"
                    n_csv = export_tt_csv(reg, csv_path)
                    score = score_self_scrape(csv_path, window_s=5.0,
                                              baseline_windows=4)
                    out["self_scrape"] = {
                        "capture_file": os.path.relpath(
                            csv_path,
                            os.path.dirname(os.path.abspath(__file__))),
                        "samples": n_csv,
                        "n_alerts": score["n_alerts"],
                        "alerted_subsystems":
                            score["alerted_subsystems"],
                    }
                except Exception as e:
                    out["self_scrape"] = {
                        "error": f"{type(e).__name__}: {e}"}
        except Exception:
            pass
        print(json.dumps(out))
        return 0
    except Exception as e:
        out.update({
            "device": "unavailable",
            "error": f"{type(e).__name__}: {e}",
            "device_note": diag,
        })
        print(json.dumps(out))
        return 1


def main() -> int:
    argv = list(sys.argv[1:])
    mode = _bench_mode(argv)
    if "--mode" in argv:
        i = argv.index("--mode")
        del argv[i:i + 2]
    probe_fresh = "--probe-fresh" in argv
    if probe_fresh:
        argv.remove("--probe-fresh")
    if mode == "serve":
        # serve mode is env-knob driven; stray argv must error, not
        # silently record a capture at the default configuration
        if argv:
            raise SystemExit(f"bench.py --mode serve takes no positional "
                             f"arguments (use ANOMOD_SERVE_BENCH_* env "
                             f"knobs), got {argv!r}")
        return serve_main(probe_fresh=probe_fresh)
    # replay mode keeps the historical positional contract: one optional
    # n_traces integer; anything else must error, not silently fall back
    # to the 2000-trace default (the capture would record a throughput
    # number for the wrong corpus size)
    n_traces = 2_000
    if argv:
        if len(argv) > 1 or not argv[0].isdigit():
            raise SystemExit(f"bench.py: expected a single positive "
                             f"n_traces integer, got {argv!r}")
        n_traces = int(argv[0])
    out = {
        "metric": "tt_replay_throughput",
        "value": 0.0,
        "unit": "spans/sec/chip",
        "vs_baseline": 0.0,
    }
    baseline = 1_000_000.0

    platform, diag = _resolve_platform(fresh=probe_fresh)
    import jax
    if platform == "cpu":
        # Pre-init platform pin (conftest.py technique); must run before any
        # backend-touching call in this process.
        jax.config.update("jax_platforms", "cpu")

    try:
        from anomod.io import cache as ingest_cache
        from anomod.io.dataset import bench_cache_status, load_bench_corpus
        from anomod.replay import ReplayConfig, measure_throughput
        from anomod.utils.platform import enable_jit_cache
        jit_cache_dir = enable_jit_cache()
        if jit_cache_dir is not None:
            out["jit_cache_dir"] = jit_cache_dir

        # Corpus prep through the content-addressed ingest cache: repeat
        # captures measure the kernel, not host synth.  ``parse_s`` keeps
        # the honest cold generate+concat wall (recorded at first publish),
        # ``prep_s`` is what THIS run actually paid.
        t0 = time.perf_counter()
        batch, ingest = load_bench_corpus("TT", n_traces)
        prep_s = time.perf_counter() - t0
        # The ingest throughput metric needs both regimes: the recorded
        # cold wall and a measured warm read.  The presence probe guards
        # the second load: if the first run's publish failed (read-only
        # cache dir, ENOSPC) a "warm" load would silently re-synthesize
        # the whole corpus a second time for a metric that then gets
        # discarded anyway.
        ingest_tp = None
        if ingest_cache.cache_root() is not None \
                and bench_cache_status("TT", n_traces)[0] == 1:
            _, warm = load_bench_corpus("TT", n_traces)
            if warm["cache_hit"] and warm["load_s"] > 0 \
                    and ingest["parse_s"] > 0:
                n_exp = ingest["n_experiments"]
                ingest_tp = {
                    "unit": "experiments/sec",
                    "cold": round(n_exp / ingest["parse_s"], 2),
                    "warm": round(n_exp / warm["load_s"], 2),
                    "speedup": round(ingest["parse_s"] / warm["load_s"], 2),
                }

        repeats = 3
        # Engine per backend (the BASELINE.json backend switch): the
        # sorted-window pallas kernel is the fast path on TPU (1.5e9 vs
        # 2.5e8 spans/sec for the XLA scan on v5e); the CPU fallback runs
        # the numpy scatter-add engine — the right shape for a host core
        # (~13x the XLA scan there, one-hot matmuls are wasted work on
        # CPU).  Mosaic only executes on real TPU devices — an explicit
        # ANOMOD_BENCH_KERNEL=pallas override off-TPU is therefore
        # downgraded (with a note) instead of honored into the
        # never-finishing interpret path.
        on_tpu = platform != "cpu" and jax.devices()[0].platform == "tpu"
        # per-backend default: sorted pallas on TPU, the host numpy engine
        # on the CPU fallback, the XLA path on any other accelerator (numpy
        # there would measure the host while "device" reports the
        # accelerator)
        default_kernel = "pallas-sorted" if on_tpu else \
            ("numpy" if platform == "cpu" else "xla")
        kernel = os.environ.get("ANOMOD_BENCH_KERNEL", "").strip().lower() \
            or default_kernel
        if kernel in ("pallas", "pallas-sorted") and not on_tpu:
            requested, kernel = kernel, ("numpy" if platform == "cpu"
                                         else "xla")
            out["kernel_note"] = (f"ANOMOD_BENCH_KERNEL={requested} requires "
                                  f"a TPU backend (Mosaic); downgraded to "
                                  f"{kernel}")
        # Device-side replication loops the staged corpus inside ONE
        # dispatch so the wall measures steady-state kernel rate, not the
        # fixed ~70 ms tunnel dispatch/read-back overhead.  The committed
        # replicate-scaling probe (bench_runs/...pallas_block_sweep_tpu,
        # replicate 64->1024) shows rate still rising at 64 — 4096 sits
        # within 7% of the overhead-free asymptote at ~1.3 s/dispatch.
        # Slower kernels keep 64 (~30M spans, their established protocol);
        # the CPU host engine sizes for one core.
        if kernel == "pallas-sorted":
            replicate = 4096
        elif kernel == "numpy":
            # host engine: device-sized replication would be 64 full host
            # passes per repeat — size the work for one core
            replicate = 2
        else:
            replicate = 64 if platform != "cpu" else 2
        # ANOMOD_BENCH_REPLICATE overrides the per-kernel default (used by
        # tpu_watch.sh for like-for-like 4096-replicate captures of the
        # slower kernels); ignored on the CPU fallback where device-sized
        # replication would run for hours on a host core.
        rep_env = os.environ.get("ANOMOD_BENCH_REPLICATE", "").strip()
        if rep_env and platform != "cpu":
            if rep_env.isdigit() and int(rep_env) > 0:
                replicate = int(rep_env)
            else:
                # a malformed override must not burn a live-tunnel window:
                # keep the per-kernel default and note the rejection
                out["replicate_note"] = (f"ignored malformed "
                                         f"ANOMOD_BENCH_REPLICATE={rep_env!r}")
        cfg = ReplayConfig(n_services=batch.n_services)
        # f32 exactness clamp: device kernels accumulate per-segment counts
        # in f32 across the replicate loop, losing integer exactness past
        # 2^24 per (service, window) segment — a replicate that pushes the
        # hottest segment over that trips measure_throughput's count assert
        # and burns the capture window.  Clamp from the ACTUAL staged
        # corpus (applies to the env override too; the numpy engine sums
        # per-pass in f64, so it is exempt).
        if kernel != "numpy" and replicate > 1:
            import numpy as _np

            from anomod.replay import segment_ids
            hottest = int(_np.bincount(segment_ids(batch, cfg),
                                       minlength=cfg.sw).max())
            cap = max(1, (1 << 24) // max(1, hottest))
            if replicate > cap:
                note = (f"replicate clamped {replicate}->{cap}: hottest "
                        f"segment holds {hottest} spans and f32 counts are "
                        f"exact only to 2^24")
                prior = out.get("replicate_note")
                out["replicate_note"] = f"{prior}; {note}" if prior else note
                replicate = cap
        # ANOMOD_PROFILE_DIR=<dir> wraps the measured dispatches in a
        # jax.profiler device trace (TensorBoard/Perfetto) for kernel-level
        # inspection of the replay hot loop on real hardware
        from anomod.utils.tracing import profile_to
        with profile_to(os.environ.get("ANOMOD_PROFILE_DIR")):
            result = measure_throughput(batch, cfg, repeats=repeats,
                                        replicate=replicate, kernel=kernel)

        out.update({
            "value": round(result.spans_per_sec, 1),
            "vs_baseline": round(result.spans_per_sec / baseline, 3),
            "n_spans": result.n_spans,
            "wall_s": round(result.wall_s, 4),
            "raw_wall_s": [round(t, 4) for t in result.raw_wall_s],
            "compile_s": round(result.compile_s, 2),
            "prep_s": round(prep_s, 4),
            "parse_s": round(ingest["parse_s"], 4),
            "cache_hit": bool(ingest["cache_hit"]),
            "kernel": result.kernel,
            "replicate_used": replicate,
            "device": str(jax.devices()[0]),
        })
        if ingest_tp is not None:
            out["tt_ingest_throughput"] = ingest_tp
        # the run's own telemetry (anomod.obs): cache traffic + replay
        # compile/dispatch book, inline so every capture line carries its
        # metrics snapshot (the serve mode additionally exports the full
        # self-scrape time series)
        try:
            from anomod.obs import get_registry
            out["obs_snapshot"] = get_registry().snapshot()
        except Exception:
            pass
        if platform == "cpu":
            out["device_note"] = diag
        # Committed provenance trail: every successful capture is also written
        # as a bench_runs/ record (device string + versions + git SHA), so
        # on-chip numbers survive as re-checkable artifacts even if the
        # device tunnel is dead by the time the driver runs.
        try:
            from anomod.provenance import capture_record, write_capture
            rec = capture_record(out["metric"], out["value"], out["unit"],
                                 **{k: v for k, v in out.items()
                                    if k not in ("metric", "value", "unit")})
            path = write_capture(rec)
            if path:
                out["capture_file"] = os.path.relpath(
                    path, os.path.dirname(os.path.abspath(__file__)))
        except Exception:
            pass
        print(json.dumps(out))
        return 0
    except Exception as e:  # still emit the JSON line with diagnostics
        out.update({
            "device": "unavailable",
            "error": f"{type(e).__name__}: {e}",
            "device_note": diag,
        })
        print(json.dumps(out))
        return 1


if __name__ == "__main__":
    sys.exit(main())
